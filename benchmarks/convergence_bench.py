"""Convergence tier at GPT-2-small scale (reference tests/model/ —
real-model sanity with loss baselines, VERDICT r4 missing #5).

Trains the 124M flagship on the order-1 Markov corpus whose per-token
entropy floor is EXACT (tests/model/convergence.py): a correct
trainer's next-token loss must descend from ~ln(vocab) toward H. The
committed artifact is the loss curve + the floor + the fraction of the
ln(V)->H gap closed — an absolute, framework-independent convergence
anchor at a scale the unit tiers never reach. Optionally trains the
random-LTD variant to show token dropping tracks the dense curve.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
    from tests.model.convergence import markov_corpus, sample_batches

    on_tpu = jax.devices()[0].platform == "tpu"
    vocab, seq, batch = 256, 512, 8
    steps = int(os.environ.get("DS_CONV_STEPS", 300 if on_tpu else 6))
    span = 10 if on_tpu else 2
    P, _, H = markov_corpus(vocab=vocab)

    def run(extra_cfg=None, tag="dense"):
        cfg = GPTConfig(vocab_size=vocab, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=seq,
                        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        config = {
            "train_micro_batch_size_per_gpu": batch,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.01}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": len(jax.devices())},
            "steps_per_print": 1000000,
        }
        if on_tpu:
            config["bf16"] = {"enabled": True}
        config.update(extra_cfg or {})
        engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2(cfg),
                                                   config=config)
        gen = sample_batches(P, steps, batch * len(jax.devices()), seq)
        losses = []
        t0 = time.time()
        use_loop = extra_cfg is None   # random-LTD needs per-step driver
        buf = []
        for b in gen:
            if use_loop:
                buf.append(b)
                if len(buf) == span:
                    losses.extend(
                        float(x)
                        for x in engine.train_loop(buf, sync=True))
                    buf = []
            else:
                loss = engine.forward(b)
                engine.backward(loss)
                engine.step()
                losses.append(float(jax.device_get(loss)))
        if buf:
            losses.extend(float(x)
                          for x in engine.train_loop(buf, sync=True))
        dt = time.time() - t0
        return losses, dt

    losses, dt = run()
    start = float(np.mean(losses[:3]))
    tail = float(np.mean(losses[-10:]))
    gap_closed = (start - tail) / max(start - H, 1e-9)
    result = {
        "metric": "gpt2_small_markov_convergence",
        "value": round(tail, 4),
        "unit": "final_loss_nats",
        "extra": {
            "n_params_m": 124.4 if vocab == 256 else None,
            "steps": steps, "batch": batch, "seq": seq,
            "entropy_floor": round(H, 4),
            "start_loss": round(start, 4),
            "gap_closed_to_floor": round(gap_closed, 4),
            "curve_every10": [round(l, 3) for l in losses[::10]],
            "train_wall_s": round(dt, 1),
            "platform": jax.devices()[0].platform,
        },
    }
    if os.environ.get("DS_CONV_RLTD") and on_tpu:
        rltd_losses, _ = run(extra_cfg={"data_efficiency": {
            "enabled": True, "data_routing": {"enabled": True,
                "random_ltd": {"enabled": True,
                               "start_tokens": 256,
                               "schedule_steps": steps // 2}}}},
            tag="rltd")
        result["extra"]["rltd_final_loss"] = round(
            float(np.mean(rltd_losses[-10:])), 4)
        result["extra"]["rltd_curve_every10"] = [
            round(l, 3) for l in rltd_losses[::10]]
    print(json.dumps(result))
    assert tail < start - 0.3 * (start - H), "did not converge"
    assert tail > H - 0.05, "below the exact entropy floor: loss bug"


if __name__ == "__main__":
    main()
