"""Decode-kernel perf regression guard (VERDICT r2 #8).

Times the Pallas KV-decode kernel against the jnp reference at serving
shapes on the real chip and FAILS (exit 1) if the kernel is slower —
the guard that keeps the `softmax_context`-equivalent kernel earning
its keep. Prints one JSON line per shape.

Run on TPU: python benchmarks/decode_guard.py
(off-TPU it reports interpret-mode numbers and skips the assertion).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SHAPES = [
    # (batch, heads, kv_heads, head_dim, cache_len)  — serving shapes
    (1, 12, 12, 64, 1024),     # gpt2-small single stream
    (8, 12, 12, 64, 1024),     # small batch serving
    (1, 32, 8, 128, 2048),     # llama-7B-ish GQA
]


def time_fn(fn, args, iters=50):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    # fence through a host transfer (axon relay; see bench.py)
    float(jax.device_get(out.sum()))
    return (time.time() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention.decode import decode_attention
    from deepspeed_tpu.ops.attention.reference import mha_reference
    from deepspeed_tpu.ops.attention.decode import _repeat_kv

    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    ok = True
    for b, h, kv_h, d, L in SHAPES:
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, L, kv_h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, L, kv_h, d)), jnp.bfloat16)
        # validity mask for a 3/4-full cache
        pos = np.zeros((b, 1, 1, L), np.float32)
        pos[..., 3 * L // 4:] = -1e30
        bias = jnp.asarray(pos)

        # force_kernel: off-TPU decode_attention now routes interpret
        # mode to the jnp reference (serving hot path); this guard
        # exists to time the KERNEL, so pin it explicitly
        kernel = jax.jit(lambda q, k, v, bias: decode_attention(
            q, k, v, bias=bias, force_kernel=True))

        def ref(q, k, v, bias):
            kf = _repeat_kv(k, h // kv_h)
            vf = _repeat_kv(v, h // kv_h)
            return mha_reference(q, kf, vf, causal=False, bias=bias)

        ref_j = jax.jit(ref)
        t_kernel = time_fn(kernel, (q, k, v, bias))
        t_ref = time_fn(ref_j, (q, k, v, bias))
        speedup = t_ref / t_kernel
        row = {"metric": "decode_kernel_speedup_vs_jnp",
               "value": round(speedup, 3), "unit": "x",
               "extra": {"shape": [b, h, kv_h, d, L],
                         "kernel_us": round(t_kernel * 1e6, 1),
                         "jnp_us": round(t_ref * 1e6, 1),
                         "platform": jax.default_backend()}}
        print(json.dumps(row))
        if on_tpu and speedup < 1.0:
            ok = False
    if on_tpu and not ok:
        print("FAIL: decode kernel slower than the jnp reference",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
