"""Perf-floor check: fresh serving-bench JSON vs the committed results.

ROADMAP item 5's perf-regression gate, phase 2: the two STABLEST ratio
metrics now **gate** (exit nonzero on breach, no flag needed), the
noisier ones stay warn-only behind ``--gate``.

What is compared (only sections present in BOTH files):

* **gating ratios** — self-normalizing ratios whose both sides ran on
  the same machine in the same process, observed stable across the
  committed rounds and CI history, each with its own documented noise
  band:

  - ``speedup_best_h_vs_h1`` (committed 2.04x; band 0.40 → floor
    ~1.22x: the fused-horizon win has never measured below 1.6x on any
    rig, so a sub-1.22x reading is a real regression, not noise);
  - ``cluster.prefix.aggregate_prefix_hit_rate`` (committed 0.75 = its
    workload ceiling; band 0.15 → floor ~0.64, still above the 0.583
    round-robin baseline: routing is deterministic, so a breach means
    prefix-aware placement actually broke).

  ``--warn-only`` demotes gating rows to warnings (bring-up escape
  hatch).
* **warn-only ratios** (``--gate`` flips them fatal) — prefix-share and
  spec-decode speedups (workload-sensitive), with the shared ``--band``.
* **tracing overhead** — ``tracing.overhead_frac`` must stay under an
  absolute ceiling (the "tracing is near-free" contract).
* **absolute tokens/s** — printed for trend visibility, never warned
  on across rigs.

Usage:
  python benchmarks/perf_floor.py \
      --committed benchmarks/serving_results_cpu.json \
      --fresh serving_results_ci.json [--band 0.30] [--gate] [--warn-only]
"""

import argparse
import json
import sys


def _get(d, path):
    for k in path.split("."):
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d if isinstance(d, (int, float)) else None


# (label, json path, kind, band) — kind "gate": fresh >=
# committed*(1-band) with the row's OWN band, breach is fatal unless
# --warn-only; "ratio": same bound with the shared --band, warn-only
# unless --gate; "ceiling": fresh <= limit; "info": printed only
CHECKS = [
    ("horizon speedup (best H vs H=1)", "speedup_best_h_vs_h1",
     "gate", 0.40),
    ("continuous vs static speedup", "speedup", "ratio", None),
    ("prefix-cache speedup (shared)",
     "prefix_share.shared.speedup_tokens_per_sec", "ratio", None),
    ("prefix-cache control (no share)",
     "prefix_share.control.speedup_tokens_per_sec", "info", None),
    ("spec-decode speedup", "spec_decode.speedup_tokens_per_sec",
     "ratio", None),
    ("cluster prefix hit rate",
     "cluster.prefix.aggregate_prefix_hit_rate", "gate", 0.15),
    ("cluster hit-rate gain vs round-robin", "cluster.hit_rate_gain",
     "info", None),
    ("tracing overhead frac", "tracing.overhead_frac", "ceiling", None),
    # comm-telemetry rows (PR 12): the per-dispatch capture + watchdog
    # cost stays under the same near-free ceiling as span tracing (the
    # ledger analysis compile runs off the timed path by design); the
    # bytes-per-token figure is the comms scorecard ROADMAP items 4
    # (shard_mapped kernels on real meshes) and 5 (cross-host KV
    # transport) must land like-for-like against — info, never gating
    ("comm-telemetry overhead frac", "comm.overhead_frac", "ceiling",
     None),
    ("comm wire bytes/token (decode)", "comm.bytes_per_token", "info",
     None),
    ("comm wire bytes/step (decode)", "comm.bytes_per_step", "info",
     None),
    # memory-telemetry rows (PR 11): overhead stays informational like
    # the other telemetry numbers on shared CI runners; the steady-state
    # prefix-cache occupancy fraction is the capacity trend line the
    # quantized-KV work and the autotuner's prefix_cache_pages knob
    # will price against — info, never gating
    ("mem-telemetry overhead frac", "memory.overhead_frac",
     "info", None),
    ("prefix-cache occupancy frac (steady state)",
     "memory.occupancy_frac", "info", None),
    ("mem page-seconds (shared workload)",
     "memory.mem_on.page_seconds_total", "info", None),
    # serving-autotuner rows (PR 13): the tuned config must at least
    # match the default on the committed prefix-share mix (a ratio
    # around the committed ~4x — the tuner rediscovering the prefix
    # cache + best horizon), and the cost model's predicted-vs-measured
    # rank correlation is its honesty trend line.  Info for now —
    # search measurements on shared CI runners carry horizon-sweep
    # noise; the acceptance test pins the >= 1 and > 0 directions
    ("tuned vs default tokens/s (prefix mix)", "tuning.tuned_vs_default",
     "info", None),
    ("tuned-config tokens/s", "tuning.tuned.tokens_per_sec",
     "info", None),
    ("cost-model rank correlation", "tuning.search.rank_correlation",
     "info", None),
    ("continuous tokens/s (best H)", "continuous.tokens_per_sec",
     "info", None),
    ("tracing tokens/s (on)", "tracing.trace_on.tokens_per_sec",
     "info", None),
    # quantized-serving-memory rows (PR 14): the capacity ratio is pure
    # page arithmetic over committed byte figures (deterministic — a
    # gate CANDIDATE once a couple of CI rounds confirm it never moves
    # off its 3.2x), the equal-byte capacity speedup and the same-slots
    # int8-vs-fp32 tokens/s ratio are CPU-rig dequant prices that a TPU
    # kernel run will re-anchor — info first, per the PR-8/11 pattern
    ("kv-quant capacity ratio (pages @ equal bytes)",
     "kv_quant.capacity.capacity_ratio", "info", None),
    ("kv-quant capacity speedup (equal bytes)",
     "kv_quant.capacity.speedup_tokens_per_sec", "info", None),
    ("kv-quant same-slots int8 vs fp32 tokens/s",
     "kv_quant.same_slots.speedup_tokens_per_sec", "info", None),
    ("kv-quant int8 tokens/s (equal bytes)",
     "kv_quant.capacity.int8.tokens_per_sec", "info", None),
    # decoding-policy rows (PR 16): the sampled-vs-greedy throughput
    # ratio prices the on-device logit pipeline (fp32 processing +
    # categorical draws per token on a CPU rig — a TPU round will
    # re-anchor); grammar validity must sit at 1.0 and the policy
    # path's extra compiles near 0 (bucket coverage noise only) — info
    # rows first, per the telemetry-PR pattern
    ("sampled vs greedy tokens/s (policy mix)",
     "sampling.sampled_vs_greedy", "info", None),
    ("sampled tokens/s (policy mix)",
     "sampling.sampled.tokens_per_sec", "info", None),
    ("grammar-constrained tokens/s",
     "sampling.grammar.tokens_per_sec", "info", None),
    ("grammar schema-valid frac",
     "sampling.grammar.grammar_valid_frac", "info", None),
    ("policy-path extra compiles (timed repeats)",
     "sampling.policy_extra_compiles", "info", None),
    # shard_map'd paged-kernel rows (PR 15): on CPU the kernel column
    # prices interpret-mode EMULATION (expected << 1 — it proves the
    # dispatch, not a win); the ratio becomes the real scorecard when
    # the first TPU sweep lands like-for-like in the same JSON paths.
    # Info, never gating, until a TPU round anchors the numbers
    ("mesh kernel/reference ratio (2x4, interpret on CPU)",
     "mesh_sweep.sweep.2x4.kernel_vs_reference", "info", None),
    ("mesh kernel/reference ratio (1x8, interpret on CPU)",
     "mesh_sweep.sweep.1x8.kernel_vs_reference", "info", None),
    ("mesh kernel tokens/s (2x4)",
     "mesh_sweep.sweep.2x4.kernel.tokens_per_sec", "info", None),
    # sequence-parallel long-context rows (PR 18): on CPU every rank of
    # the 'sequence' axis shares the host's cores, so these numbers
    # bound DISPATCH/orchestration overhead (the sp leg runs ~axis-size
    # x fewer, wider prefill dispatches), not chip scaling — and the
    # 64k chunked baseline is a labeled power-law extrapolation (a
    # measured run costs ~1h on a 1-core rig).  Info, never gating,
    # until a TPU round lands like-for-like in the same JSON paths
    ("long-context TTFT sp/chunked @16k (CPU: dispatch bound)",
     "long_context.curve.16384.ttft_ratio", "info", None),
    ("long-context TTFT sp/extrapolated-chunked @64k",
     "long_context.curve.65536.ttft_ratio_vs_extrapolated", "info",
     None),
    ("long-context sp TTFT @64k (ms, CPU rig)",
     "long_context.curve.65536.seq_parallel.ttft_ms_p50", "info", None),
    ("long-context sp prefill compiles (whole curve)",
     "long_context.seq_prefill_compiles", "info", None),
    # cross-host disagg transport rows (PR 19): the wire figures price
    # HOST-staged loopback frames on a CPU rig (two worker processes
    # time-slicing one machine), so they bound protocol/relay overhead,
    # not DCN bandwidth — a real multi-host round re-anchors MB/s in
    # the same JSON paths.  The TTFT ratio is the process-boundary tax
    # against the identical in-process chunked transfer (device_put);
    # bytes/handoff is deterministic page arithmetic the bench already
    # gates exactly, carried here as the trend line.  Info, never
    # gating, until a multi-host round lands like-for-like
    ("disagg wire transfer MB/s (DCN ledger, loopback rig)",
     "disagg.wire.handoff_mb_per_s", "info", None),
    ("disagg TTFT wire vs device_put (p50 ratio)",
     "disagg.ttft_ratio_wire_vs_device_put", "info", None),
    ("disagg wire bytes per handoff (exact by construction)",
     "disagg.wire.bytes_per_handoff", "info", None),
    # multi-tenant multi-LoRA rows (PR 20): the slowdown ratio prices
    # the per-slot adapter gather + rank-bucket delta einsums on a CPU
    # rig (the cost model's _fit_reference_terms reads this exact
    # path); the fairness share is the two weighted tenants'
    # page-seconds split over one pool — both re-anchor on a TPU
    # round in the same JSON paths.  Info, never gating
    ("multi-LoRA slowdown (base vs 8 adapters)",
     "multi_lora.slowdown_tokens_per_sec", "info", None),
    ("multi-LoRA tokens/s (8 adapters)",
     "multi_lora.lora_8.tokens_per_sec", "info", None),
    ("multi-LoRA gold-tenant page-seconds share",
     "multi_lora.lora_8.fairness.page_seconds_share.gold", "info",
     None),
]

TRACING_OVERHEAD_CEILING = 0.05   # the committed <5% contract


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--committed",
                   default="benchmarks/serving_results_cpu.json")
    p.add_argument("--fresh", required=True)
    p.add_argument("--band", type=float, default=0.30,
                   help="allowed fractional regression on warn-only "
                        "ratio metrics (default 0.30 — CI-runner noise "
                        "on 2-core machines is real); gating rows carry "
                        "their own documented bands")
    p.add_argument("--gate", action="store_true",
                   help="also exit 1 on warn-only ratio WARNs "
                        "(default: gating rows only)")
    p.add_argument("--warn-only", action="store_true",
                   help="demote gating rows to warnings (bring-up "
                        "escape hatch)")
    args = p.parse_args()

    with open(args.committed) as f:
        committed = json.load(f)
    with open(args.fresh) as f:
        fresh = json.load(f)

    rows = []
    warns = 0
    gate_fails = 0
    for label, path, kind, band in CHECKS:
        c, fv = _get(committed, path), _get(fresh, path)
        if kind == "ceiling":
            if fv is None:
                rows.append((label, c, fv, "SKIP"))
                continue
            ok = fv <= TRACING_OVERHEAD_CEILING + args.band * \
                TRACING_OVERHEAD_CEILING
            rows.append((label, TRACING_OVERHEAD_CEILING, fv,
                         "PASS" if ok else "WARN"))
            warns += not ok
            continue
        if c is None or fv is None:
            rows.append((label, c, fv, "SKIP"))
            continue
        if kind == "info":
            rows.append((label, c, fv, "INFO"))
            continue
        floor = c * (1.0 - (band if kind == "gate" else args.band))
        ok = fv >= floor
        if kind == "gate" and not args.warn_only:
            rows.append((label, c, fv, "PASS" if ok else "FAIL"))
            gate_fails += not ok
        else:
            rows.append((label, c, fv, "PASS" if ok else "WARN"))
            warns += not ok

    w = max(len(r[0]) for r in rows)
    print(f"perf floor vs {args.committed} "
          f"(warn band {args.band:.0%}; gating rows use their own):")
    print(f"{'metric':{w}s} {'committed':>12s} {'fresh':>12s} {'':>6s}")
    for label, c, fv, verdict in rows:
        cs = "-" if c is None else f"{c:.4g}"
        fs = "-" if fv is None else f"{fv:.4g}"
        print(f"{label:{w}s} {cs:>12s} {fs:>12s} {verdict:>6s}")
    print(f"{gate_fails} gate failure(s), {warns} warning(s)")
    if gate_fails:
        sys.exit(1)
    if args.gate and warns:
        sys.exit(1)


if __name__ == "__main__":
    main()
