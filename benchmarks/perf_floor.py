"""Perf-floor check: fresh serving-bench JSON vs the committed results.

The stepping stone to ROADMAP item 5's gating perf-regression check:
compare a fresh ``serving_bench.py`` results file against the committed
``benchmarks/serving_results_cpu.json`` with EXPLICIT noise bands and
print a pass/warn table.  Non-gating by default (CI runners and the
committed rig are different machines, so absolute tokens/s are
reported informationally only); ``--gate`` flips warnings into a
nonzero exit for the day the bands are trusted.

What is compared (only sections present in BOTH files):

* **ratio metrics** — speedups and hit rates are self-normalizing
  (both sides of each ratio ran on the same machine in the same
  process), so they transfer across rigs and carry a tight band:
  ``speedup_best_h_vs_h1``, continuous-vs-static ``speedup``,
  prefix-share and spec-decode speedups, cluster hit-rate gain.
* **tracing overhead** — ``tracing.overhead_frac`` must stay under an
  absolute ceiling (the "tracing is near-free" contract).
* **absolute tokens/s** — printed for trend visibility, never warned
  on across rigs.

Usage:
  python benchmarks/perf_floor.py \
      --committed benchmarks/serving_results_cpu.json \
      --fresh serving_results_ci.json [--band 0.30] [--gate]
"""

import argparse
import json
import sys


def _get(d, path):
    for k in path.split("."):
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d if isinstance(d, (int, float)) else None


# (label, json path, kind) — kind "ratio": fresh >= committed*(1-band);
# "ceiling": fresh <= limit (committed value ignored for the bound);
# "info": printed only
CHECKS = [
    ("horizon speedup (best H vs H=1)", "speedup_best_h_vs_h1", "ratio"),
    ("continuous vs static speedup", "speedup", "ratio"),
    ("prefix-cache speedup (shared)",
     "prefix_share.shared.speedup_tokens_per_sec", "ratio"),
    ("prefix-cache control (no share)",
     "prefix_share.control.speedup_tokens_per_sec", "info"),
    ("spec-decode speedup", "spec_decode.speedup_tokens_per_sec",
     "ratio"),
    ("cluster prefix hit rate",
     "cluster.prefix.aggregate_prefix_hit_rate", "ratio"),
    ("cluster hit-rate gain vs round-robin", "cluster.hit_rate_gain",
     "info"),
    ("tracing overhead frac", "tracing.overhead_frac", "ceiling"),
    ("continuous tokens/s (best H)", "continuous.tokens_per_sec",
     "info"),
    ("tracing tokens/s (on)", "tracing.trace_on.tokens_per_sec",
     "info"),
]

TRACING_OVERHEAD_CEILING = 0.05   # the committed <5% contract


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--committed",
                   default="benchmarks/serving_results_cpu.json")
    p.add_argument("--fresh", required=True)
    p.add_argument("--band", type=float, default=0.30,
                   help="allowed fractional regression on ratio metrics "
                        "before a WARN (default 0.30 — CI-runner noise "
                        "on 2-core machines is real)")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 on any WARN (default: report only)")
    args = p.parse_args()

    with open(args.committed) as f:
        committed = json.load(f)
    with open(args.fresh) as f:
        fresh = json.load(f)

    rows = []
    warns = 0
    for label, path, kind in CHECKS:
        c, fv = _get(committed, path), _get(fresh, path)
        if kind == "ceiling":
            if fv is None:
                rows.append((label, c, fv, "SKIP"))
                continue
            ok = fv <= TRACING_OVERHEAD_CEILING + args.band * \
                TRACING_OVERHEAD_CEILING
            rows.append((label, TRACING_OVERHEAD_CEILING, fv,
                         "PASS" if ok else "WARN"))
            warns += not ok
            continue
        if c is None or fv is None:
            rows.append((label, c, fv, "SKIP"))
            continue
        if kind == "info":
            rows.append((label, c, fv, "INFO"))
            continue
        floor = c * (1.0 - args.band)
        ok = fv >= floor
        rows.append((label, c, fv, "PASS" if ok else "WARN"))
        warns += not ok

    w = max(len(r[0]) for r in rows)
    print(f"perf floor vs {args.committed} "
          f"(noise band {args.band:.0%}):")
    print(f"{'metric':{w}s} {'committed':>12s} {'fresh':>12s} {'':>6s}")
    for label, c, fv, verdict in rows:
        cs = "-" if c is None else f"{c:.4g}"
        fs = "-" if fv is None else f"{fv:.4g}"
        print(f"{label:{w}s} {cs:>12s} {fs:>12s} {verdict:>6s}")
    print(f"{warns} warning(s)")
    if args.gate and warns:
        sys.exit(1)


if __name__ == "__main__":
    main()
