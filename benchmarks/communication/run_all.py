"""Collective benchmark sweep (reference benchmarks/communication/run_all.py
+ bin/ds_bench): psum / all_gather / reduce_scatter / all_to_all /
ppermute over the active mesh, across message sizes, reporting latency
and algorithmic/bus bandwidth via the comms logger's formulas.

Usage:
    python benchmarks/communication/run_all.py [--axis data]
        [--maxsize 26] [--trials 5] [--dtype float32] [--json out.json]

Runs on whatever devices are visible (one TPU chip -> trivial loopback;
the 8-device virtual CPU mesh exercises real collectives; a TPU pod
exercises ICI).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--axis", default="data")
    p.add_argument("--maxsize", type=int, default=24,
                   help="log2 of the largest message in bytes")
    p.add_argument("--minsize", type=int, default=16)
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--warmups", type=int, default=2)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,"
                                    "all_to_all,ppermute,"
                                    "compressed_allreduce")
    p.add_argument("--json", default=None)
    args = p.parse_args()

    if os.environ.get("DSTPU_BENCH_CPU"):
        # must land before jax initializes: older jax (<0.5) has no
        # jax_num_cpu_devices option, only the XLA flag
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = flags + \
                " --xla_force_host_platform_device_count=" + \
                os.environ["DSTPU_BENCH_CPU"]
    import jax
    if os.environ.get("DSTPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices",
                              int(os.environ.get("DSTPU_BENCH_CPU")))
        except AttributeError:
            pass   # jax<0.5: XLA_FLAGS above already set the count
    import jax.numpy as jnp
    from jax import lax
    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.comm.telemetry import bench_row, write_ledger_json
    from deepspeed_tpu.parallel.topology import make_mesh

    if dist.get_mesh() is None:
        dist.set_mesh(make_mesh())
    mesh = dist.get_mesh()
    ax = args.axis
    n = mesh.shape[ax]
    dtype = jnp.dtype(args.dtype)
    print(f"# mesh={dict(mesh.shape)} axis={ax} n={n} "
          f"platform={jax.default_backend()}", file=sys.stderr)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def compressed(x):
        # 1-bit error-feedback allreduce (runtime/comm/compressed.py):
        # sign bits + one scale per phase on the wire
        from deepspeed_tpu.runtime.comm.compressed import \
            compressed_allreduce
        we = jnp.zeros_like(x)
        pad = (-x.size) % (n * 8)
        se = jnp.zeros((x.size + pad) // n, x.dtype)
        out, _, _ = compressed_allreduce(x, we, se, ax)
        return out

    OPS = {
        "all_reduce": lambda x: lax.psum(x, ax),
        "all_gather": lambda x: lax.all_gather(x, ax, tiled=True),
        "reduce_scatter": lambda x: lax.psum_scatter(x, ax, tiled=True),
        "all_to_all": lambda x: lax.all_to_all(
            x.reshape(n, -1), ax, 0, 0, tiled=False).reshape(-1),
        "ppermute": lambda x: lax.ppermute(x, ax, perm),
        "compressed_allreduce": compressed,
    }
    results = []
    for op_name in args.ops.split(","):
        fn = OPS[op_name]
        size = 1 << args.minsize
        while size <= (1 << args.maxsize):
            elems = max(size // dtype.itemsize, n * n)
            elems -= elems % (n * n)      # per-shard length must also
                                          # divide by n (scatter/all2all)
            x = jnp.asarray(np.random.default_rng(0)
                            .standard_normal(elems), dtype)
            times = []
            for t in range(args.warmups + args.trials):
                t0 = time.time()
                out = dist.eager_collective(fn, x, group=ax,
                                            op_name=op_name)
                jax.block_until_ready(out)
                dt = time.time() - t0
                if t >= args.warmups:
                    times.append(dt)
            lat = float(np.median(times))
            # the canonical comm-ledger row schema (comm/telemetry.py)
            # — bench_row expects the per-rank message size and applies
            # the op's own bw scaling via calc_bw_log
            row = bench_row(
                "all_reduce" if op_name == "compressed_allreduce"
                else op_name, size // max(n, 1), lat, n, axis=ax)
            # keep bench_row's canonical op-scaled bytes so offline
            # rows join runtime ledger_rows exactly; only the op name
            # is restored (compressed_allreduce rides all_reduce's
            # bandwidth formulas)
            row["op"] = op_name
            if op_name == "compressed_allreduce" and n > 1:
                # bytes-on-wire per rank: each rank quantizes its LOCAL
                # shard (eager_collective splits dim 0 over the axis) and
                # ships sign bits in both phases — but all_to_all out and
                # all_gather back each keep 1/n of the payload local, so
                # only (n-1)/n of the sign bits cross the wire per phase,
                # plus the n scales; vs 2*(n-1)/n * shard for a ring
                # allreduce at this dtype. All wire fields are skipped at
                # n == 1 where nothing leaves the chip.
                shard = elems // n
                offchip = (n - 1) / n
                wire = int(2 * offchip * (shard // 8)) \
                    + 2 * (n - 1) * dtype.itemsize
                row["wire_bytes_per_rank"] = wire
                row["uncompressed_allreduce_wire_bytes"] = int(
                    2 * offchip * shard * dtype.itemsize)
                row["compression_x"] = round(
                    row["uncompressed_allreduce_wire_bytes"] / wire, 2)
            results.append(row)
            print(json.dumps(row))
            size <<= 2
    if args.json:
        # committed rounds survive re-runs under previous_committed
        write_ledger_json(args.json, {"mesh": dict(mesh.shape),
                                      "axis": ax, "results": results})
        print(f"# wrote {args.json}", file=sys.stderr)


if __name__ == "__main__":
    main()
