"""Inference latency benchmark (reference benchmarks/inference/gpt-bench.py
+ bert-bench.py).

Decoder models: prefill latency and per-token decode latency through the
KV-cache generation path, optionally with int8 weight quantization.
Encoder models (bert-*): single-forward latency p50/p90 swept over
(batch, seq) pairs — the reference bert-bench.py grid. Prints one
bench.py-style JSON line per configuration.

Usage: python benchmarks/inference_bench.py [--model gpt2-small]
       [--batch 1] [--prompt 128] [--tokens 64] [--dtypes bfloat16,int8]
       python benchmarks/inference_bench.py --model bert-large \
           [--encoder-sweep 1:128,8:128,1:512,8:512] [--trials 20]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(model_name, batch, prompt_len, new_tokens, dtype):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, gpt2_small
    from deepspeed_tpu.models.llama import Llama, llama_tiny

    import jax.numpy as jnp
    if model_name == "gpt2-small":
        module = GPT2(gpt2_small(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16))
        quant = {}
    elif model_name == "gpt-2b7":
        # GPT-Neo-2.7B-shaped decoder: the model class weight-only int8
        # serving exists for (multi-GB weights streaming from HBM each
        # token). 2.65B params: bf16 5.3GB, int8 ~2.7GB.
        from deepspeed_tpu.models.gpt2 import GPTConfig
        module = GPT2(GPTConfig(
            vocab_size=50257, hidden_size=2560, num_layers=32,
            num_heads=32, max_seq_len=2048, dtype=jnp.bfloat16,
            param_dtype=jnp.bfloat16))
        quant = {"group_size": 128}
    else:
        raise ValueError(model_name)
    vocab = module.cfg.vocab_size

    engine = deepspeed_tpu.init_inference(
        module, dtype=dtype, max_out_tokens=prompt_len + new_tokens + 8,
        **({"quant": quant} if quant and dtype == "int8" else {}))
    engine.init_params()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, prompt_len)).astype("i4")

    # dispatch round-trip constant: on a tunneled/relayed rig this is
    # ~100 ms of pure host<->device latency paid once per dispatch — NOT
    # per-token compute. Measure it and report decode numbers with it
    # subtracted from the (single-dispatch) fused decode loop.
    import time
    import jax
    import jax.numpy as jnp
    triv = jax.jit(lambda x: jnp.sum(x))
    float(jax.device_get(triv(jnp.zeros(8))))
    rt = []
    for _ in range(5):
        t0 = time.time()
        float(jax.device_get(triv(jnp.zeros(8))))
        rt.append(time.time() - t0)
    overhead_ms = float(np.median(rt)) * 1e3

    # warmup (compile prefill + fused decode loop at the measured shape)
    engine.generate(ids, max_new_tokens=new_tokens)
    engine.model_times()

    # the relay constant jitters by tens of ms run to run — take medians
    # over several whole-generate trials
    trials = 7
    prefills, totals = [], []
    for _ in range(trials):
        out = engine.generate(ids, max_new_tokens=new_tokens)
        times = engine.model_times()
        assert out.shape[1] == prompt_len + new_tokens
        prefills.append(times[0] * 1e3)
        totals.append(float(np.sum(times[1:])) * 1e3)
        n = len(times) - 1
    # times[1:] spread ONE fused-loop dispatch evenly, so the dispatch
    # constant is the loop total's overhead, not each token's
    raw_total = float(np.median(totals))
    adj_total = max(raw_total - overhead_ms, 1e-9)
    per_tok = adj_total / n
    return {
        "prefill_ms": round(float(np.median(prefills)) - overhead_ms, 3),
        # the fused loop is ONE dispatch: only the mean per-token time is
        # measurable (no per-token tail percentiles)
        "token_mean_ms": round(per_tok, 3),
        "decode_tokens_per_sec": round(batch * n / (adj_total / 1e3), 1),
        "dispatch_overhead_ms": round(overhead_ms, 3),
        "raw_decode_total_ms": round(raw_total, 3),
        "trials": trials,
    }


def run_encoder(model_name, sweep, dtype, trials):
    """BERT encoder latency rows (reference benchmarks/inference/
    bert-bench.py: fill-mask pipeline latency over a batch x seq grid;
    here the MLM forward through init_inference, p50/p90 over trials)."""
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models.bert import Bert, bert_large, bert_tiny

    import jax.numpy as jnp
    cfgs = {"bert-large": bert_large, "bert-tiny": bert_tiny}
    module = Bert(cfgs[model_name](dtype=jnp.bfloat16,
                                   param_dtype=jnp.bfloat16))
    engine = deepspeed_tpu.init_inference(module, dtype=dtype)
    engine.init_params(example_ids=jnp.zeros((1, 8), jnp.int32))
    vocab = module.cfg.vocab_size
    rng = np.random.default_rng(0)

    rows = []
    for batch, seq in sweep:
        ids = rng.integers(0, vocab, (batch, seq)).astype("i4")
        mask = np.ones((batch, seq), "i4")
        engine.forward(ids, attention_mask=mask)      # compile
        engine.model_times()
        for _ in range(trials):
            engine.forward(ids, attention_mask=mask)
        times = np.asarray(engine.model_times()) * 1e3
        rows.append({
            "batch": batch, "seq": seq,
            "latency_ms_p50": round(float(np.percentile(times, 50)), 3),
            "latency_ms_p90": round(float(np.percentile(times, 90)), 3),
            "seq_per_sec": round(batch / (np.percentile(times, 50) / 1e3), 1),
            "trials": trials,
        })
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-small",
                   choices=["gpt2-small", "gpt-2b7", "bert-tiny",
                            "bert-large"])
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--tokens", type=int, default=64)
    p.add_argument("--dtypes", default="bfloat16,int8")
    p.add_argument("--encoder-sweep", default="1:128,8:128,1:512,8:512",
                   help="batch:seq pairs for encoder models")
    p.add_argument("--trials", type=int, default=20)
    args = p.parse_args()

    if args.model.startswith("bert"):
        sweep = [tuple(int(x) for x in pair.split(":"))
                 for pair in args.encoder_sweep.split(",")]
        dtype = args.dtypes.split(",")[0]
        for r in run_encoder(args.model, sweep, dtype, args.trials):
            print(json.dumps({
                "metric": f"{args.model}_{dtype}_encoder_latency"
                          f"_b{r['batch']}_s{r['seq']}",
                "value": r["latency_ms_p50"], "unit": "ms",
                "extra": {**r, "dtype": dtype},
            }))
        return

    for dtype in args.dtypes.split(","):
        r = run(args.model, args.batch, args.prompt, args.tokens, dtype)
        print(json.dumps({
            "metric": f"{args.model}_{dtype}_decode_token_latency",
            "value": r["token_mean_ms"], "unit": "ms",
            "extra": {**r, "batch": args.batch, "prompt": args.prompt,
                      "new_tokens": args.tokens, "dtype": dtype},
        }))


if __name__ == "__main__":
    main()
