"""Inference latency benchmark (reference benchmarks/inference/gpt-bench.py).

Measures prefill latency and per-token decode latency (p50/p90) through
the KV-cache generation path, optionally with int8 weight quantization.
Prints one bench.py-style JSON line per configuration.

Usage: python benchmarks/inference_bench.py [--model gpt2-small]
       [--batch 1] [--prompt 128] [--tokens 64] [--dtypes bfloat16,int8]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(model_name, batch, prompt_len, new_tokens, dtype):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, gpt2_small
    from deepspeed_tpu.models.llama import Llama, llama_tiny

    if model_name == "gpt2-small":
        import jax.numpy as jnp
        module = GPT2(gpt2_small(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16))
        vocab = module.cfg.vocab_size
    else:
        raise ValueError(model_name)

    engine = deepspeed_tpu.init_inference(
        module, dtype=dtype, max_out_tokens=prompt_len + new_tokens + 8)
    engine.init_params()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, prompt_len)).astype("i4")

    # warmup (compile prefill + fused decode loop at the measured shape)
    engine.generate(ids, max_new_tokens=new_tokens)
    engine.model_times()

    out = engine.generate(ids, max_new_tokens=new_tokens)
    times = engine.model_times()
    assert out.shape[1] == prompt_len + new_tokens
    prefill_ms = times[0] * 1e3
    decode_ms = np.asarray(times[1:]) * 1e3
    return {
        "prefill_ms": round(float(prefill_ms), 3),
        "token_p50_ms": round(float(np.percentile(decode_ms, 50)), 3),
        "token_p90_ms": round(float(np.percentile(decode_ms, 90)), 3),
        "decode_tokens_per_sec":
            round(batch * len(decode_ms) / (decode_ms.sum() / 1e3), 1),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-small")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--tokens", type=int, default=64)
    p.add_argument("--dtypes", default="bfloat16,int8")
    args = p.parse_args()

    for dtype in args.dtypes.split(","):
        r = run(args.model, args.batch, args.prompt, args.tokens, dtype)
        print(json.dumps({
            "metric": f"{args.model}_{dtype}_decode_p50_latency",
            "value": r["token_p50_ms"], "unit": "ms",
            "extra": {**r, "batch": args.batch, "prompt": args.prompt,
                      "new_tokens": args.tokens, "dtype": dtype},
        }))


if __name__ == "__main__":
    main()
