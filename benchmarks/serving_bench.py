"""Serving throughput benchmark: continuous batching vs static batching.

A Poisson-arrival load generator drives the same request set through

  (a) the continuous-batching scheduler (serving/ScheduleScheduler:
      iteration-level joins, paged KV cache), and
  (b) a static-batching baseline: FIFO batches of --batch requests,
      left-padded to the batch's longest prompt, every request held
      until the slowest in its batch finishes (the pre-serving
      `generate()` regime).

Arrivals are replayed open-loop against the wall clock: a request is
only visible to either system once its (simulated) arrival time has
passed. Reports aggregate tokens/s plus TTFT/TPOT/TBT percentiles and
page-pool utilization, one bench.py-style JSON line per system.

The continuous system is additionally swept over fused decode HORIZONS
(--horizons, default 1,2,4,8): H=1 is the legacy one-dispatch-per-token
loop, larger H amortize the host round-trip over H tokens per dispatch
(`ServingScheduler(decode_horizon_steps=H)`), with the overlapped
host/device loop on by default. TBT (time between token bursts) is the
client-visible streaming cadence — the latency price of a horizon.

Usage: python benchmarks/serving_bench.py [--model gpt2-tiny]
       [--requests 32] [--rate 4.0] [--seed 0] [--horizons 1,2,4,8]
       [--json-out results.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_workload(vocab, n_requests, rate, seed):
    """Mixed-length prompts + Poisson arrival offsets."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, int(rng.integers(4, 24))).astype("i4")
               for _ in range(n_requests)]
    max_new = [int(rng.integers(4, 16)) for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    return prompts, max_new, arrivals


def run_continuous(engine, prompts, max_new, arrivals, cfg, horizon=8,
                   overlap=True):
    from deepspeed_tpu.serving import ServingScheduler
    sched = ServingScheduler(
        engine, num_slots=cfg["num_slots"], num_pages=cfg["num_pages"],
        page_size=cfg["page_size"],
        max_pages_per_slot=cfg["max_pages_per_slot"],
        prefill_chunk=cfg["prefill_chunk"],
        decode_horizon_steps=horizon, overlap=overlap)
    t0 = time.time()
    pending = list(zip(prompts, max_new, arrivals))
    submitted = []
    while True:
        now = time.time() - t0
        while pending and pending[0][2] <= now:
            p, m, _ = pending.pop(0)
            submitted.append(sched.submit(p, max_new_tokens=m))
        work = sched.step()
        if not work:
            if not pending:
                break
            # idle until the next arrival
            time.sleep(max(pending[0][2] - (time.time() - t0), 0.0))
    wall = time.time() - t0
    toks = sum(len(r.out_tokens) for r in submitted)
    out = sched.metrics.summary(wall)
    out.update({"wall_s": round(wall, 3), "tokens": toks,
                "tokens_per_sec": round(toks / wall, 2)})
    return out


def run_static(engine, prompts, max_new, arrivals, batch):
    """FIFO batches; each batch left-pads prompts to its longest and
    decodes max(max_new) steps — slot time is held by the slowest
    request (throughput baseline, not a token-for-token oracle)."""
    t0 = time.time()
    ttft, done_t = [], []
    toks = 0
    i = 0
    while i < len(prompts):
        j = min(i + batch, len(prompts))
        # a batch launches only once all of its members have arrived
        wait = arrivals[j - 1] - (time.time() - t0)
        if wait > 0:
            time.sleep(wait)
        batch_prompts = prompts[i:j]
        batch_new = max_new[i:j]
        longest = max(len(p) for p in batch_prompts)
        ids = np.zeros((j - i, longest), np.int32)
        for b, p in enumerate(batch_prompts):
            ids[b, longest - len(p):] = p      # left-pad
        t_launch = time.time()
        out = engine.generate(ids, max_new_tokens=max(batch_new),
                              do_sample=False)
        t_done = time.time()
        for b in range(j - i):
            ttft.append(t_done - t0 - arrivals[i + b])
            done_t.append(t_done - t0)
            toks += batch_new[b]               # useful tokens only
        del out
        i = j
    wall = max(done_t)
    return {
        "wall_s": round(wall, 3), "tokens": toks,
        "tokens_per_sec": round(toks / wall, 2),
        "ttft_ms_p50": round(float(np.percentile(ttft, 50)) * 1e3, 3),
        "ttft_ms_p90": round(float(np.percentile(ttft, 90)) * 1e3, 3),
        "ttft_ms_p99": round(float(np.percentile(ttft, 99)) * 1e3, 3),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-tiny",
                   choices=["gpt2-tiny", "gpt2-small"])
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=4.0,
                   help="Poisson arrival rate (req/s)")
    p.add_argument("--batch", type=int, default=4,
                   help="static-baseline batch size")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--num-pages", type=int, default=64)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-slot", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--horizons", default="1,2,4,8",
                   help="comma-separated fused decode horizons to sweep "
                        "for the continuous system")
    p.add_argument("--no-overlap", action="store_true",
                   help="disable the overlapped host/device loop")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", default=None)
    args = p.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, gpt2_small, gpt2_tiny

    cfgs = {"gpt2-tiny": gpt2_tiny, "gpt2-small": gpt2_small}
    module = GPT2(cfgs[args.model]())
    engine = deepspeed_tpu.init_inference(
        module, dtype="float32", kv_cache_dtype="float32",
        max_out_tokens=args.max_pages_per_slot * args.page_size)
    engine.init_params()
    vocab = module.cfg.vocab_size

    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    cfg = {k: getattr(args, k) for k in
           ("num_slots", "num_pages", "page_size", "max_pages_per_slot",
            "prefill_chunk")}

    horizons = [int(h) for h in args.horizons.split(",") if h.strip()]
    overlap = not args.no_overlap

    # warmup: compile every signature both systems will hit (the serving
    # primitives at every swept horizon's bucket set, plus generate() at
    # each static batch/length bucket)
    for h in horizons:
        run_continuous(engine, prompts[:4], max_new[:4], np.zeros(4), cfg,
                       horizon=h, overlap=overlap)
    run_static(engine, prompts, [1] * len(prompts), np.zeros(len(prompts)),
               args.batch)

    sweep = {}
    for h in horizons:
        r = run_continuous(engine, prompts, max_new, arrivals, cfg,
                           horizon=h, overlap=overlap)
        sweep[str(h)] = {k: r[k] for k in
                         ("tokens_per_sec", "wall_s", "tokens",
                          "ttft_ms_p50", "ttft_ms_p99",
                          "tbt_ms_p50", "tbt_ms_p99",
                          "tpot_ms_p50", "tpot_ms_p99",
                          "horizon_mean", "device_wait_frac",
                          "preemptions") if k in r}
        sweep[str(h)]["full"] = r
    best_h = max(sweep, key=lambda h: sweep[h]["tokens_per_sec"])
    cont = sweep[best_h]["full"]
    stat = run_static(engine, prompts, max_new, arrivals, args.batch)

    results = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "static_batch": args.batch,
        "overlap": overlap,
        "horizon_sweep": {h: {k: v for k, v in r.items() if k != "full"}
                          for h, r in sweep.items()},
        "best_horizon": int(best_h),
        "continuous": cont, "static": stat,
        "speedup": round(cont["tokens_per_sec"] / stat["tokens_per_sec"], 3)
        if stat["tokens_per_sec"] else None,
        "speedup_best_h_vs_h1": round(
            cont["tokens_per_sec"] / sweep["1"]["tokens_per_sec"], 3)
        if "1" in sweep and sweep["1"]["tokens_per_sec"] else None,
    }
    for h in sorted(sweep, key=int):
        print(json.dumps({
            "metric": "serving_continuous_tokens_per_sec",
            "value": sweep[h]["tokens_per_sec"], "unit": "tok/s",
            "extra": {"horizon": int(h),
                      **{k: v for k, v in sweep[h].items() if k != "full"}},
        }))
    print(json.dumps({
        "metric": "serving_static_tokens_per_sec",
        "value": stat["tokens_per_sec"], "unit": "tok/s", "extra": stat,
    }))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
