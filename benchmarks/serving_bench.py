"""Serving throughput benchmark: continuous batching vs static batching.

A Poisson-arrival load generator drives the same request set through

  (a) the continuous-batching scheduler (serving/ScheduleScheduler:
      iteration-level joins, paged KV cache), and
  (b) a static-batching baseline: FIFO batches of --batch requests,
      left-padded to the batch's longest prompt, every request held
      until the slowest in its batch finishes (the pre-serving
      `generate()` regime).

Arrivals are replayed open-loop against the wall clock: a request is
only visible to either system once its (simulated) arrival time has
passed. Reports aggregate tokens/s plus TTFT/TPOT/TBT percentiles and
page-pool utilization, one bench.py-style JSON line per system.

The continuous system is additionally swept over fused decode HORIZONS
(--horizons, default 1,2,4,8): H=1 is the legacy one-dispatch-per-token
loop, larger H amortize the host round-trip over H tokens per dispatch
(`ServingScheduler(decode_horizon_steps=H)`), with the overlapped
host/device loop on by default. TBT (time between token bursts) is the
client-visible streaming cadence — the latency price of a horizon.

`--prefix-share` switches to the radix-prefix-cache workload: N
requests sharing a long system prompt with distinct tails (plus a
zero-share control of equal-length distinct prompts), each served with
the prefix cache ON vs OFF — the cache-on run should win tokens/s and
TTFT roughly in proportion to the shared fraction, while the control
stays within noise of cache-off.

`--spec-decode` switches to the speculative-decoding workload:
repetition-friendly prompts (a motif repeated per prompt, distinct
across prompts) served greedy with the n-gram/prompt-lookup drafter ON
vs OFF at identical settings — the speedup is acceptance-rate driven
(each verify round costs ~one fused target forward and yields
accepted+1 tokens), and the output is token-identical either way.

Usage: python benchmarks/serving_bench.py [--model gpt2-tiny]
       [--requests 32] [--rate 4.0] [--seed 0] [--horizons 1,2,4,8]
       [--prefix-share [--shared-prefix-len 96] [--tail-len 8]]
       [--spec-decode [--spec-k 8]]
       [--json-out results.json]
"""

import argparse
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_workload(vocab, n_requests, rate, seed):
    """Mixed-length prompts + Poisson arrival offsets."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, int(rng.integers(4, 24))).astype("i4")
               for _ in range(n_requests)]
    max_new = [int(rng.integers(4, 16)) for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    return prompts, max_new, arrivals


def make_prefix_workload(vocab, n_requests, rate, seed, shared_len,
                         tail_len, share=True):
    """The --prefix-share workload: N requests sharing one long system
    prompt with distinct short tails (share=True — the radix cache's
    target traffic), or fully distinct prompts of the SAME total length
    (share=False — the zero-share control that must sit within noise of
    cache-off)."""
    rng = np.random.default_rng(seed)
    sys_prompt = rng.integers(0, vocab, shared_len).astype("i4")
    prompts = []
    for _ in range(n_requests):
        if share:
            tail = rng.integers(0, vocab, tail_len).astype("i4")
            prompts.append(np.concatenate([sys_prompt, tail]))
        else:
            prompts.append(rng.integers(0, vocab,
                                        shared_len + tail_len).astype("i4"))
    max_new = [int(rng.integers(4, 16)) for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    return prompts, max_new, arrivals


def make_spec_workload(vocab, n_requests, rate, seed, motif_len=8,
                       motif_repeats=3, tail_len=4):
    """The --spec-decode workload: repetition-friendly prompts (a short
    motif repeated several times plus a distinct tail) with LONG decode
    budgets — the traffic shape where prompt-lookup drafting earns its
    keep (summarization/extraction/code: outputs quote their context).
    The budgets matter as much as the prompts: the drafter only hits
    once the model's greedy stream settles into its repeating regime,
    so the first ~dozen tokens of every request are warmup that spec
    decode cannot speed up — long generations amortize it, short ones
    are dominated by it.  Every request's motif is distinct, so nothing
    here leans on the prefix cache."""
    rng = np.random.default_rng(seed)
    prompts, max_new = [], []
    for _ in range(n_requests):
        motif = rng.integers(0, vocab, motif_len).astype("i4")
        tail = rng.integers(0, vocab, tail_len).astype("i4")
        prompts.append(np.concatenate([np.tile(motif, motif_repeats),
                                       tail]))
        max_new.append(int(rng.integers(72, 97)))
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    return prompts, max_new, arrivals


def run_continuous(engine, prompts, max_new, arrivals, cfg, horizon=8,
                   overlap=True, prefix_cache=False,
                   prefix_cache_pages=None, spec_decode=None,
                   spec_k=8, retry_max=6, retry_backoff_s=0.05,
                   tracer=None, mem_telemetry=False, comm_telemetry=False,
                   kv_dtype=None, sched_out=None, policy=None,
                   requests_out=None, seq_parallel_threshold=0,
                   tenancy=None):
    from deepspeed_tpu.serving import QueueFull, ServingScheduler
    sched = ServingScheduler(
        engine, num_slots=cfg["num_slots"], num_pages=cfg["num_pages"],
        page_size=cfg["page_size"],
        max_pages_per_slot=cfg["max_pages_per_slot"],
        prefill_chunk=cfg["prefill_chunk"],
        decode_horizon_steps=horizon, overlap=overlap,
        prefix_cache=prefix_cache,
        prefix_cache_pages=prefix_cache_pages,
        spec_decode=spec_decode, spec_k=spec_k,
        tracer=tracer, mem_telemetry=mem_telemetry,
        comm_telemetry=comm_telemetry, kv_dtype=kv_dtype,
        seq_parallel_threshold=seq_parallel_threshold,
        tenancy=tenancy)
    if sched_out is not None:
        sched_out.append(sched)
    t0 = time.time()
    # policy: optional per-request decoding-policy rows aligned with
    # prompts — {"sampling": ..., "seed": ..., "grammar": ...} or None
    # for a greedy request (the sampled-workload leg of the bench)
    pol = policy if policy is not None else [None] * len(prompts)
    pending = list(zip(prompts, max_new, arrivals, pol))
    submitted = []
    # bounded retry with jitter on QueueFull: a burst that trips
    # backpressure re-offers each refused request after an exponential
    # backoff (jittered so the retry burst cannot re-synchronize)
    # instead of erroring out of the bench.  Retries are REPORTED, not
    # folded into latency: t_submit starts at the accepted submission,
    # so TTFT prices serving time, and the refusal cost shows up in the
    # dedicated counters below.
    retry_rng = np.random.default_rng(0xC1)
    retry_q = []                 # (due_time, prompt, max_new, attempt)
    retries = retry_dropped = 0

    def offer(p, m, row, attempt):
        nonlocal retries, retry_dropped
        try:
            submitted.append(sched.submit(p, max_new_tokens=m,
                                          **(row or {})))
        except QueueFull:
            retries += 1
            if attempt >= retry_max:
                retry_dropped += 1
                return
            delay = retry_backoff_s * (2 ** attempt) * \
                (1.0 + retry_rng.random())
            retry_q.append((time.time() - t0 + delay, p, m, row,
                            attempt + 1))
            retry_q.sort(key=lambda x: x[0])

    while True:
        now = time.time() - t0
        while retry_q and retry_q[0][0] <= now:
            _, p, m, row, attempt = retry_q.pop(0)
            offer(p, m, row, attempt)
        while pending and pending[0][2] <= now:
            p, m, _, row = pending.pop(0)
            offer(p, m, row, 0)
        work = sched.step()
        if not work:
            if not pending and not retry_q:
                break
            # idle until the next arrival or retry
            gates = [g for g in
                     ([pending[0][2]] if pending else []) +
                     ([retry_q[0][0]] if retry_q else [])]
            time.sleep(max(min(gates) - (time.time() - t0), 0.0))
    wall = time.time() - t0
    toks = sum(len(r.out_tokens) for r in submitted)
    out = sched.metrics.summary(wall)
    out.update({"wall_s": round(wall, 3), "tokens": toks,
                "tokens_per_sec": round(toks / wall, 2),
                "queue_full_retries": retries,
                "retry_dropped": retry_dropped})
    if prefix_cache:
        h = sched.health()
        out.update({k: h[k] for k in
                    ("prefix_hit_rate", "tokens_reused", "pages_shared",
                     "cached_pages", "cow_copies")})
    if policy is not None:
        h = sched.health()
        out.update({k: h[k] for k in
                    ("sampled_requests", "grammar_requests",
                     "policy_dispatches", "grammar_violations")})
        out["finished"] = sum(r.state == "finished" for r in submitted)
    if requests_out is not None:
        requests_out.extend(submitted)
    if mem_telemetry:
        out.update(sched.mem.summary_fields())
    out["mesh_info"] = sched.mesh_info
    return out


def run_static(engine, prompts, max_new, arrivals, batch):
    """FIFO batches; each batch left-pads prompts to its longest and
    decodes max(max_new) steps — slot time is held by the slowest
    request (throughput baseline, not a token-for-token oracle)."""
    t0 = time.time()
    ttft, done_t = [], []
    toks = 0
    i = 0
    while i < len(prompts):
        j = min(i + batch, len(prompts))
        # a batch launches only once all of its members have arrived
        wait = arrivals[j - 1] - (time.time() - t0)
        if wait > 0:
            time.sleep(wait)
        batch_prompts = prompts[i:j]
        batch_new = max_new[i:j]
        longest = max(len(p) for p in batch_prompts)
        ids = np.zeros((j - i, longest), np.int32)
        for b, p in enumerate(batch_prompts):
            ids[b, longest - len(p):] = p      # left-pad
        t_launch = time.time()
        out = engine.generate(ids, max_new_tokens=max(batch_new),
                              do_sample=False)
        t_done = time.time()
        for b in range(j - i):
            ttft.append(t_done - t0 - arrivals[i + b])
            done_t.append(t_done - t0)
            toks += batch_new[b]               # useful tokens only
        del out
        i = j
    wall = max(done_t)
    return {
        "wall_s": round(wall, 3), "tokens": toks,
        "tokens_per_sec": round(toks / wall, 2),
        "ttft_ms_p50": round(float(np.percentile(ttft, 50)) * 1e3, 3),
        "ttft_ms_p90": round(float(np.percentile(ttft, 90)) * 1e3, 3),
        "ttft_ms_p99": round(float(np.percentile(ttft, 99)) * 1e3, 3),
    }


def _write_json_out(path, key, section, fresh):
    """Merge ``section`` under ``key`` into an existing results file, or
    write ``fresh`` when the file is missing/unreadable: refreshing one
    workload section must not clobber the committed horizon-sweep/
    static/prefix_share/previous_committed data other runs produced."""
    out = fresh
    if os.path.exists(path):
        try:
            with open(path) as f:
                out = json.load(f)
            out[key] = section
        except (OSError, ValueError):
            out = fresh
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")


_PREFIX_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
                "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "preemptions",
                "page_util_peak", "prefix_hit_rate", "prefill_tokens_saved",
                "cache_evictions", "tokens_reused", "pages_shared",
                "cached_pages", "cow_copies")


def run_prefix_share(engine, vocab, cfg, args, horizon, overlap):
    """Cache-on vs cache-off over the shared-prefix workload plus the
    zero-share control (which must land within noise of cache-off: a
    cache that only helps when prefixes actually repeat)."""
    # the section carries its own run metadata: the merge path below
    # drops it into a results file whose top-level model/requests/rate
    # may come from a DIFFERENT standard run with different settings
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap,
        "shared_prefix_len": args.shared_prefix_len,
        "tail_len": args.tail_len,
        "shared_fraction": round(args.shared_prefix_len /
                                 (args.shared_prefix_len + args.tail_len),
                                 3),
        "horizon": horizon,
    }
    for name, share in (("shared", True), ("control", False)):
        prompts, max_new, arrivals = make_prefix_workload(
            vocab, args.requests, args.rate, args.seed,
            args.shared_prefix_len, args.tail_len, share=share)
        entry = {}
        for label, pc in (("cache_off", False), ("cache_on", True)):
            # warmup: one full untimed replay of the workload — the
            # staggered arrivals produce batched-sampling shapes (and
            # the COW page-copy signature) an all-at-once pass never
            # compiles, and they must not land in the timed run
            run_continuous(engine, prompts, max_new, arrivals, cfg,
                           horizon=horizon, overlap=overlap,
                           prefix_cache=pc)
            # best-of-N: the cache's WORK is deterministic (hit rates
            # and tokens saved repeat exactly); only the wall clock is
            # noisy on shared/throttled rigs, so the fastest replay is
            # the least-perturbed measurement of the same computation
            r = None
            for _ in range(max(1, args.repeats)):
                cand = run_continuous(engine, prompts, max_new, arrivals,
                                      cfg, horizon=horizon,
                                      overlap=overlap, prefix_cache=pc)
                if r is None or cand["tokens_per_sec"] > \
                        r["tokens_per_sec"]:
                    r = cand
            entry[label] = {k: r[k] for k in _PREFIX_KEYS if k in r}
        off, on = entry["cache_off"], entry["cache_on"]
        entry["speedup_tokens_per_sec"] = round(
            on["tokens_per_sec"] / off["tokens_per_sec"], 3) \
            if off["tokens_per_sec"] else None
        entry["ttft_p50_speedup"] = round(
            off["ttft_ms_p50"] / on["ttft_ms_p50"], 3) \
            if on["ttft_ms_p50"] else None
        section[name] = entry
        print(json.dumps({
            "metric": f"serving_prefix_share_{name}_speedup",
            "value": entry["speedup_tokens_per_sec"], "unit": "x",
            "extra": entry,
        }))
    results = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap,
        "prefix_share": section,
    }
    if args.json_out:
        _write_json_out(args.json_out, "prefix_share", section, results)
    return results


_MESH_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
              "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "horizon_mean",
              "device_wait_frac", "preemptions", "page_util_peak")


def run_mesh_sweep(module, vocab, cfg, args, horizon, overlap):
    """Serve the standard mixed workload on each requested device-mesh
    shape (model x data) plus the 1-device baseline, all in one process
    over the forced CPU device pool.  On CPU the mesh shapes share two
    physical cores, so the numbers establish the HARNESS and the
    sharding/dispatch overhead bound — not a speedup claim (that needs
    real chips); the committed section exists so a TPU run has a
    like-for-like schema to land in."""
    import jax
    import deepspeed_tpu

    shapes = [(1, 1)]
    for part in args.mesh.split(","):
        part = part.strip().lower()
        if not part:
            continue
        try:
            m, d = (int(x) for x in part.split("x"))
        except ValueError:
            raise SystemExit(f"--mesh: cannot parse {part!r}; expected "
                             "MODELxDATA shapes like '1x8,2x4,4x2'")
        if (m, d) not in shapes:
            shapes.append((m, d))
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "devices_available": len(jax.devices()),
        "backend": jax.default_backend(),
        "note": "CPU mesh shapes share the same physical cores: this "
                "measures sharded-serving correctness + dispatch "
                "overhead, not chip-scaling speedup; the kernel column "
                "runs the shard_map'd Pallas paged kernel in interpret "
                "mode (emulation price on CPU — the same leg is the "
                "real kernel measurement on TPU)",
        "sweep": {},
    }
    def measure_leg(m, d, paged_kernel="auto"):
        """Build one mesh engine and measure the standard workload:
        untimed warmup (the shape's full signature set) then best-of
        --repeats.  ONE code path for the reference and kernel columns,
        so the two legs can never drift methodologically."""
        engine = deepspeed_tpu.init_inference(
            module, dtype="float32", kv_cache_dtype="float32",
            tensor_parallel={"tp_size": m}, mesh={"data": d, "model": m},
            paged_kernel=paged_kernel,
            max_out_tokens=cfg["max_pages_per_slot"] * cfg["page_size"])
        engine.init_params()
        run_continuous(engine, prompts, max_new, arrivals, cfg,
                       horizon=horizon, overlap=overlap)
        r = None
        for _ in range(max(1, args.repeats)):
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap)
            if r is None or cand["tokens_per_sec"] > r["tokens_per_sec"]:
                r = cand
        return engine, r

    for m, d in shapes:
        engine, r = measure_leg(m, d)
        entry = {k: r[k] for k in _MESH_KEYS if k in r}
        entry["mesh"] = {"model": m, "data": d}
        entry["decode_multi_compiles"] = \
            engine.serving_decode_multi_compile_count()
        # the timed scheduler already snapshotted the live topology —
        # no second pool allocation just to read byte counts
        info = r.get("mesh_info") or {}
        entry["kv_pool_bytes_per_device"] = \
            info.get("kv_pool_bytes_per_device")
        entry["serving_axes"] = info.get("serving_axes")
        entry["paged_attention"] = info.get("paged_attention")

        # kernel-vs-reference column: the SAME workload through a
        # paged_kernel="force" engine — the shard_map'd Pallas kernel
        # per shard (interpret mode on CPU, where it prices emulation
        # overhead, not a win; on real TPU this exact leg is the
        # like-for-like kernel measurement the sweep exists for).
        # 1x1 keeps its single-device kernel leg too, as the baseline.
        if getattr(args, "mesh_kernel", True):
            _, kr = measure_leg(m, d, paged_kernel="force")
            entry["kernel"] = {
                "tokens_per_sec": kr["tokens_per_sec"],
                "wall_s": kr["wall_s"],
                "paged_attention":
                    (kr.get("mesh_info") or {}).get("paged_attention"),
            }
            entry["kernel_vs_reference"] = round(
                kr["tokens_per_sec"] / entry["tokens_per_sec"], 3) \
                if entry["tokens_per_sec"] else None
        section["sweep"][f"{m}x{d}"] = entry
        print(json.dumps({
            "metric": "serving_mesh_tokens_per_sec",
            "value": entry["tokens_per_sec"], "unit": "tok/s",
            "extra": entry,
        }))
    base = section["sweep"]["1x1"]["tokens_per_sec"]
    for key, entry in section["sweep"].items():
        entry["vs_1x1"] = round(entry["tokens_per_sec"] / base, 3) \
            if base else None
    if args.json_out:
        _write_json_out(
            args.json_out, "mesh_sweep", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "mesh_sweep": section})
    return section


_LC_KEYS = ("ttft_ms_p50", "tokens_per_sec", "wall_s", "tokens",
            "tbt_ms_p50", "preemptions")

_LC_NOTE = ("CPU rig: every rank of the 'sequence' mesh axis shares the "
            "host's cores, so sequence-parallel compute does NOT get "
            "faster math here — the curve bounds DISPATCH/orchestration "
            "overhead (the sp leg runs ~axis-size x fewer, wider prefill "
            "dispatches) and proves the routed path end-to-end at real "
            "long-context lengths; both legs still pay the O(L^2) "
            "attention math serially, so 'scaling broken' shows up as "
            "the sp/chunked TTFT ratio falling with length, not as "
            "absolute sub-linear TTFT.  Legs whose projected cost "
            "exceeds --lc-leg-budget-s carry a labeled extrapolation, "
            "never a fabricated measurement.  Chip-scaling TTFT wins "
            "need a TPU run landing in these same JSON paths")


def run_long_context(cfg, args, horizon, overlap):
    """TTFT-vs-prompt-length curve: sequence-parallel prefill vs plain
    chunked prefill at otherwise identical settings.

    One engine on a pure ``{"sequence": N}`` mesh serves both legs of
    every length — the ONLY knob that differs between legs is the
    scheduler's ``seq_parallel_threshold`` (0 = chunked baseline), so
    the comparison isolates the routed prefill path.  The module is the
    rotary llama fixture (no learned-position table to outgrow at 64k),
    with a head count the sequence axis divides so the bench exercises
    the Ulysses all-to-all transport.  Per length: untimed warmup when
    the leg is cheap enough to replay, then the measured legs
    interleave for --repeats rounds and each keeps its best
    (minimum-TTFT) round — the prefix-share methodology: the work is
    greedy and deterministic, so the best replay is the least
    clock-perturbed measurement.  A leg whose projected cost exceeds
    --lc-leg-budget-s (the chunked baseline is O(L^2) and costs ~1h at
    64k on a 1-core rig) is skipped with the reason + a labeled
    power-law extrapolation recorded in its place."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import Llama, llama_tiny

    lengths = sorted({int(x) for x in args.lengths.split(",") if x.strip()})
    seq = len(jax.devices())
    max_new = 8
    page_size = cfg["page_size"]
    # threshold below the shortest swept length so every length routes;
    # the chunked leg passes 0 (routing off) at identical settings
    thr = max(1, min(256, lengths[0] // 2))
    mcfg = llama_tiny(hidden_size=32, intermediate_size=64, num_layers=1,
                      num_heads=8, num_kv_heads=4,
                      max_seq_len=lengths[-1] + max_new + page_size)
    module = Llama(mcfg)
    engine = deepspeed_tpu.init_inference(
        module, dtype="float32", kv_cache_dtype="float32",
        mesh={"sequence": seq},
        max_out_tokens=lengths[-1] + max_new)
    engine.init_params()
    plan = engine.seq_parallel_plan()
    if plan is None or not plan.usable:
        raise SystemExit(
            "--long-context needs a multi-device 'sequence' mesh axis; "
            "on CPU force one with XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 first")

    rng = np.random.default_rng(args.seed)
    budget_ms = args.lc_leg_budget_s * 1000.0
    history = {"chunked": [], "seq_parallel": []}   # (length, ttft_ms)
    section = {
        "model": (f"llama-tiny(rotary; hidden={mcfg.hidden_size}, "
                  f"layers={mcfg.num_layers}, heads={mcfg.num_heads}, "
                  f"kv_heads={mcfg.num_kv_heads})"),
        "mesh": {"sequence": seq}, "backend": jax.default_backend(),
        "devices_available": seq,
        "transport": plan.impl, "prefill_chunk": cfg["prefill_chunk"],
        "page_size": page_size, "seq_parallel_threshold": thr,
        "max_new_tokens": max_new, "repeats": args.repeats,
        "note": _LC_NOTE, "curve": {},
    }
    sp_health = {}
    for length in lengths:
        pages = -(-(length + max_new) // page_size)
        cfg_l = {"num_slots": 1, "num_pages": pages + 2,
                 "page_size": page_size, "max_pages_per_slot": pages + 2,
                 "prefill_chunk": cfg["prefill_chunk"]}
        prompts = [rng.integers(0, mcfg.vocab_size, length).astype("i4")]
        new = [max_new]
        arr = np.zeros(1)

        def leg(threshold):
            sched_out = []
            r = run_continuous(engine, prompts, new, arr, cfg_l,
                               horizon=horizon, overlap=overlap,
                               seq_parallel_threshold=threshold,
                               sched_out=sched_out)
            if threshold and not sp_health:
                h = sched_out[0].health()
                sp_health.update({k: h[k] for k in
                                  ("seq_parallel_axis",
                                   "seq_parallel_impl",
                                   "sp_chunk_buckets")})
            return r

        entry = {
            "prompt_tokens": length, "pages_reserved": pages,
            # the mechanism under test: dispatch-count asymmetry
            "chunked_prefill_dispatches":
                -(-length // cfg["prefill_chunk"]),
        }
        # per-leg time budget: a leg whose PROJECTED cost (quadratic
        # scale-up of its last measured length — attention over the
        # padded chain is O(L^2)) exceeds --lc-leg-budget-s is skipped
        # with the reason recorded and a clearly-labeled power-law
        # extrapolation in its place, instead of silently stalling CI
        # for an hour on a 1-core rig.  Warmup replays only when the
        # leg is cheap enough to run twice+ (compile noise at the big
        # lengths is <2% of a multi-minute TTFT, noted per entry).
        plan_legs = {}
        for name, t in (("chunked", 0), ("seq_parallel", thr)):
            hist = history[name]
            proj = hist[-1][1] * (length / hist[-1][0]) ** 2 \
                if hist else 0.0
            if proj > budget_ms:
                exp_ = 2.0
                if len(hist) >= 2:
                    (l0, t0), (l1, t1) = hist[-2], hist[-1]
                    exp_ = math.log(t1 / t0) / math.log(l1 / l0)
                entry[name] = {
                    "skipped": (f"projected ~{proj / 1000:.0f}s/run on "
                                "this rig exceeds --lc-leg-budget-s="
                                f"{args.lc_leg_budget_s:g}"),
                    "ttft_ms_extrapolated": round(
                        hist[-1][1] *
                        (length / hist[-1][0]) ** exp_, 1),
                    "extrapolation": (f"power-law exponent {exp_:.2f} "
                                      "fit to the last two measured "
                                      "lengths — NOT a measurement"),
                }
                continue
            plan_legs[name] = (t, proj <= budget_ms / 4.0)
        for name, (t, warm) in plan_legs.items():
            if warm:
                leg(t)                     # untimed warmup (compiles)
        best = {name: None for name in plan_legs}
        for _ in range(max(1, args.repeats)):
            for name, (t, _) in plan_legs.items():   # interleaved legs
                cand = leg(t)
                if best[name] is None or \
                        cand["ttft_ms_p50"] < best[name]["ttft_ms_p50"]:
                    best[name] = cand
        for name, b in best.items():
            history[name].append((length, b["ttft_ms_p50"]))
            entry[name] = {k: b[k] for k in _LC_KEYS if k in b}
            entry[name]["warmed_up"] = plan_legs[name][1]
            entry[name]["ttft_ms_per_1k_tokens"] = round(
                b["ttft_ms_p50"] * 1024.0 / length, 3)
        ch, sp = best.get("chunked"), best.get("seq_parallel")
        if sp is not None:
            if not sp["seq_prefill_routed"]:
                raise SystemExit(f"length {length}: sp leg never routed "
                                 "— threshold/plan wiring broke")
            entry["sp_prefill_dispatches"] = sp["seq_prefill_chunks"]
        if ch is not None and sp is not None and ch["ttft_ms_p50"]:
            entry["ttft_ratio"] = round(
                sp["ttft_ms_p50"] / ch["ttft_ms_p50"], 3)
        elif sp is not None and \
                entry["chunked"].get("ttft_ms_extrapolated"):
            entry["ttft_ratio_vs_extrapolated"] = round(
                sp["ttft_ms_p50"] /
                entry["chunked"]["ttft_ms_extrapolated"], 3)
        section["curve"][str(length)] = entry
        print(json.dumps({
            "metric": "long_context_ttft_ms",
            "value": entry["seq_parallel"].get("ttft_ms_p50"),
            "unit": "ms", "extra": entry,
        }))
    section.update(sp_health)
    # one jit signature per (chunk bucket, page-chain shape) — the
    # compile-count pin the oracle suite enforces per bucket
    section["seq_prefill_compiles"] = \
        engine.serving_seq_prefill_compile_count()
    if args.json_out:
        _write_json_out(args.json_out, "long_context", section,
                        {"long_context": section})
    return section


_SPEC_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
              "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "preemptions",
              "page_util_peak", "spec_dispatches", "spec_draft_tokens",
              "spec_accepted_tokens", "spec_acceptance_rate",
              "spec_mean_accepted", "spec_rollbacks",
              "spec_rollback_tokens", "spec_degraded")


def run_spec_decode(engine, vocab, cfg, args, horizon, overlap):
    """Spec-on (ngram drafter) vs spec-off over the repetition-friendly
    workload at otherwise identical settings.  The work is greedy and
    deterministic — spec decode changes only which dispatches run, not
    one output token — so like --prefix-share the best of --repeats
    replays is the least-perturbed measurement."""
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "spec_k": args.spec_k, "drafter": "ngram",
        "motif_len": args.spec_motif_len,
        "motif_repeats": args.spec_motif_repeats,
    }
    prompts, max_new, arrivals = make_spec_workload(
        vocab, args.requests, args.rate, args.seed,
        motif_len=args.spec_motif_len,
        motif_repeats=args.spec_motif_repeats)
    for label, mode in (("spec_off", None), ("spec_on", "ngram")):
        # warmup: one untimed replay compiles every signature this
        # configuration can hit (incl. the verify-K buckets)
        run_continuous(engine, prompts, max_new, arrivals, cfg,
                       horizon=horizon, overlap=overlap, spec_decode=mode,
                       spec_k=args.spec_k)
        r = None
        for _ in range(max(1, args.repeats)):
            cand = run_continuous(engine, prompts, max_new, arrivals, cfg,
                                  horizon=horizon, overlap=overlap,
                                  spec_decode=mode, spec_k=args.spec_k)
            if r is None or cand["tokens_per_sec"] > r["tokens_per_sec"]:
                r = cand
        section[label] = {k: r[k] for k in _SPEC_KEYS if k in r}
    off, on = section["spec_off"], section["spec_on"]
    section["speedup_tokens_per_sec"] = round(
        on["tokens_per_sec"] / off["tokens_per_sec"], 3) \
        if off["tokens_per_sec"] else None
    print(json.dumps({
        "metric": "serving_spec_decode_speedup",
        "value": section["speedup_tokens_per_sec"], "unit": "x",
        "extra": {"acceptance_rate": on.get("spec_acceptance_rate"),
                  "mean_accepted": on.get("spec_mean_accepted"),
                  "spec_on_tokens_per_sec": on["tokens_per_sec"],
                  "spec_off_tokens_per_sec": off["tokens_per_sec"]},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "spec_decode", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "spec_decode": section})
    return section


_LORA_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
              "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "preemptions",
              "page_util_peak", "device_wait_frac", "horizon_mean")


def run_multi_lora(engine, vocab, cfg, args, horizon, overlap):
    """Multi-tenant multi-LoRA leg: the SAME greedy workload served
    base-only (tenancy off), then striped across 1 and 8 resident
    adapters through two weighted tenants sharing one page pool.  The
    adapter factors are synthetic (seeded — deterministic across runs)
    but the decode path is the real one: per-slot gather over the
    stacked rank-bucket pack + delta einsums on every dispatch.  The
    slowdown ratio and the rank bucket are what the autotuner's cost
    model fits its multi-LoRA term to (cost_model._fit_reference_terms
    reads exactly ``multi_lora.slowdown_tokens_per_sec`` and
    ``multi_lora.rank_bucket``); the fairness table is the two tenants'
    page-seconds ledgers over the shared pool."""
    from deepspeed_tpu.serving.tenancy import (AdapterStore, TenantConfig,
                                               TenantRegistry,
                                               random_adapter)
    counts = [int(c) for c in args.lora_adapters.split(",") if c.strip()]
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "adapter_counts": counts, "adapter_rank": args.lora_rank,
    }
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    mcfg = engine.module.cfg

    def rig(n_adapters):
        """(tenancy, policy rows): two weighted tenants, requests
        striped across the adapter roster + base.  Fresh per replay —
        the usage ledgers are per-run accounting."""
        if n_adapters == 0:
            return None, None
        store = AdapterStore(mcfg)
        for i in range(n_adapters):
            store.add(f"a{i}", random_adapter(mcfg, args.lora_rank,
                                              seed=i))
        names = tuple(store.names())
        tenancy = TenantRegistry(
            [TenantConfig("gold", weight=3.0, adapters=names),
             TenantConfig("bronze", weight=1.0, adapters=names)],
            adapter_store=store)
        roster = list(names) + [None]
        rows = [{"tenant": "gold" if i % 2 == 0 else "bronze",
                 "adapter": roster[i % len(roster)]}
                for i in range(len(prompts))]
        return tenancy, rows

    rank_bucket = 0
    for n in [0] + counts:
        label = "base" if n == 0 else f"lora_{n}"
        # warmup replay compiles the rank bucket's signatures off the
        # clock (the base leg reuses the pre-tenancy signatures)
        tenancy, rows = rig(n)
        run_continuous(engine, prompts, max_new, arrivals, cfg,
                       horizon=horizon, overlap=overlap, policy=rows,
                       tenancy=tenancy)
        if tenancy is not None and tenancy.store is not None:
            rank_bucket = tenancy.store.rank_bucket()
        r = fair = None
        for _ in range(max(1, args.repeats)):
            tenancy, rows = rig(n)
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap,
                                  policy=rows, tenancy=tenancy)
            if r is None or cand["tokens_per_sec"] > r["tokens_per_sec"]:
                r = cand
                fair = None if tenancy is None else \
                    tenancy.usage_fields()
        section[label] = {k: r[k] for k in _LORA_KEYS if k in r}
        if fair is not None:
            total_ps = sum(u["page_seconds"] for u in fair.values())
            section[label]["fairness"] = {
                "weights": {"gold": 3.0, "bronze": 1.0},
                "tenants": fair,
                "page_seconds_share": {
                    t: round(u["page_seconds"] / total_ps, 4)
                    for t, u in fair.items()} if total_ps else None,
            }
    base = section["base"]["tokens_per_sec"]
    heavy = f"lora_{max(counts)}"
    section["rank_bucket"] = rank_bucket
    section["slowdown_tokens_per_sec"] = round(
        base / section[heavy]["tokens_per_sec"], 3) \
        if section[heavy]["tokens_per_sec"] else None
    for n in counts:
        lab = f"lora_{n}"
        section[lab]["vs_base_tokens_per_sec"] = round(
            section[lab]["tokens_per_sec"] / base, 3) if base else None
    print(json.dumps({
        "metric": "serving_multi_lora_slowdown",
        "value": section["slowdown_tokens_per_sec"], "unit": "x",
        "extra": {"rank_bucket": rank_bucket,
                  "adapter_counts": counts,
                  "base_tokens_per_sec": base,
                  **{f"lora_{n}_tokens_per_sec":
                     section[f"lora_{n}"]["tokens_per_sec"]
                     for n in counts}},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "multi_lora", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "multi_lora": section})
    return section


_SAMPLED_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
                 "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50",
                 "device_wait_frac", "horizon_mean", "preemptions",
                 "sampled_requests", "grammar_requests",
                 "policy_dispatches", "grammar_violations", "finished")


def make_sampled_policy(n, seed, grammar_every=0):
    """Per-request decoding-policy rows for the --sampled workload: a
    representative production mix — 1/3 greedy, 1/3 nucleus-sampled,
    1/3 sampled with penalties — each sampled request carrying its own
    seed.  grammar_every > 0 constrains every n-th request to a small
    JSON schema (those rows ride the verify-free horizon-1 path)."""
    schema = {"json_schema": {"type": "object",
                              "properties": {"ok": {"type": "boolean"},
                                             "n": {"type": "integer"}}}}
    rows = []
    for i in range(n):
        if grammar_every and i % grammar_every == 0:
            rows.append({"sampling": {"do_sample": True,
                                      "temperature": 0.9},
                         "seed": seed + i, "grammar": schema})
        elif i % 3 == 0:
            rows.append(None)
        elif i % 3 == 1:
            rows.append({"sampling": {"do_sample": True,
                                      "temperature": 0.9,
                                      "top_p": 0.95},
                         "seed": seed + i})
        else:
            rows.append({"sampling": {"do_sample": True,
                                      "temperature": 1.1, "top_k": 50,
                                      "repetition_penalty": 1.2,
                                      "frequency_penalty": 0.2},
                         "seed": seed + i})
    return rows


def run_sampled(engine, vocab, cfg, args, horizon, overlap):
    """``--sampled``: the standard workload served greedy (baseline) vs
    a mixed greedy/sampled/penalized policy mix vs the same mix with a
    grammar-constrained fraction — the decoding-policy price card.
    Per-slot policy params are traced lanes, so param churn itself
    never compiles (unit-pinned); ``policy_extra_compiles`` counts
    signatures added during the timed repeats — bounded by the horizon
    BUCKET set (arrival timing decides which buckets a replay batches
    into), near 0 in practice and never proportional to request or
    param churn.  Grammar rows must emit 100% schema-valid output
    (``grammar_valid_frac``)."""
    from deepspeed_tpu.serving.sampling import compile_grammar
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
    }
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    # grammar rows need budget to reach DFA completion (self-terminating
    # JSON): '{"ok":false,"n":-123456789}' tops out well under 32
    grammar_every = 3
    g_max_new = [max(m, 32) if i % grammar_every == 0 else m
                 for i, m in enumerate(max_new)]
    legs = (
        ("greedy", max_new, None),
        ("sampled", max_new, make_sampled_policy(args.requests,
                                                 args.seed)),
        ("grammar", g_max_new,
         make_sampled_policy(args.requests, args.seed,
                             grammar_every=grammar_every)),
    )
    extra_compiles = 0
    for label, mnew, pol in legs:
        # warmup: compile both the legacy and the policy twins at this
        # horizon bucket untimed
        run_continuous(engine, prompts, mnew, arrivals, cfg,
                       horizon=horizon, overlap=overlap,
                       policy=pol if pol is not None else [None] *
                       len(prompts))
        compiles_before_timed = engine.serving_decode_multi_compile_count()
        r = None
        reqs = []
        for _ in range(max(1, args.repeats)):
            cand_reqs = []
            cand = run_continuous(
                engine, prompts, mnew, arrivals, cfg, horizon=horizon,
                overlap=overlap,
                policy=pol if pol is not None else [None] * len(prompts),
                requests_out=cand_reqs)
            if r is None or cand["tokens_per_sec"] > r["tokens_per_sec"]:
                r, reqs = cand, cand_reqs
        extra_compiles += engine.serving_decode_multi_compile_count() \
            - compiles_before_timed
        section[label] = {k: r[k] for k in _SAMPLED_KEYS if k in r}
        if pol is not None and any(
                row and row.get("grammar") for row in pol):
            checked = valid = 0
            for req, row in zip(reqs, pol):
                if not row or not row.get("grammar"):
                    continue
                checked += 1
                gc = compile_grammar(row["grammar"], vocab)
                valid += req.state == "finished" and \
                    gc.accepts(list(req.out_tokens))
            section[label]["grammar_checked"] = checked
            section[label]["grammar_valid_frac"] = \
                round(valid / checked, 4) if checked else None
    # the compile-stability claim: each leg's timed repeats (after its
    # one warmup replay) added zero signatures — policy-param churn and
    # the greedy/sampled mix share the per-horizon executables
    section["policy_extra_compiles"] = extra_compiles
    g, s = section["greedy"], section["sampled"]
    section["sampled_vs_greedy"] = round(
        s["tokens_per_sec"] / g["tokens_per_sec"], 3) \
        if g["tokens_per_sec"] else None
    print(json.dumps({
        "metric": "serving_sampled_vs_greedy",
        "value": section["sampled_vs_greedy"], "unit": "x",
        "extra": {
            "greedy_tokens_per_sec": g["tokens_per_sec"],
            "sampled_tokens_per_sec": s["tokens_per_sec"],
            "grammar_tokens_per_sec":
                section["grammar"]["tokens_per_sec"],
            "grammar_valid_frac":
                section["grammar"].get("grammar_valid_frac"),
            "policy_extra_compiles": section["policy_extra_compiles"],
        },
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "sampling", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "sampling": section})
    return section


_TRACE_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
               "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50",
               "device_wait_frac", "horizon_mean")


def run_trace_overhead(engine, vocab, cfg, args, horizon, overlap):
    """``--trace``: the standard mixed workload served with span
    tracing OFF vs ON at identical settings — the committed results
    carry an honest tracing-overhead number (tokens/s ratio), and one
    traced repeat's per-request span JSON lands in ``--trace-out`` so
    the artifact a CI reviewer opens in Perfetto is the same workload
    the number describes.  Like the other deterministic comparisons
    the best of ``--repeats`` replays is the least-perturbed
    measurement of the same computation."""
    from deepspeed_tpu.serving.trace import SpanTracer
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
    }
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    # warmup compiles every signature untimed (tracing cannot add any:
    # it is host-only — the pinned test in test_trace.py proves it)
    run_continuous(engine, prompts, max_new, arrivals, cfg,
                   horizon=horizon, overlap=overlap)
    # INTERLEAVED repeats (off, on, off, on, ...): rig-level drift
    # (thermal/frequency ramps, cache warmth) otherwise lands entirely
    # on whichever label ran second and masquerades as tracing
    # overhead/speedup
    results = {}
    tracer = None
    for _ in range(max(1, args.repeats)):
        for label in ("trace_off", "trace_on"):
            t = SpanTracer(process="bench") if label == "trace_on" \
                else None
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap,
                                  tracer=t)
            best = results.get(label)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                results[label] = cand
                if t is not None:
                    tracer = t
    for label, best in results.items():
        section[label] = {k: best[k] for k in _TRACE_KEYS if k in best}
    off = results["trace_off"]["tokens_per_sec"]
    on = results["trace_on"]["tokens_per_sec"]
    section["overhead_frac"] = round(1.0 - on / off, 4) if off else None
    section["spans_recorded"] = len(tracer.events) + tracer.dropped
    if args.trace_out:
        tracer.dump(args.trace_out)
        section["trace_file"] = args.trace_out
    print(json.dumps({
        "metric": "serving_tracing_overhead_frac",
        "value": section["overhead_frac"], "unit": "frac",
        "extra": {"tokens_per_sec_off": off, "tokens_per_sec_on": on,
                  "spans": section["spans_recorded"]},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "tracing", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "tracing": section})
    return section


_MEM_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
             "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50",
             "device_wait_frac", "horizon_mean", "prefix_hit_rate",
             "cached_pages", "page_util_peak", "page_seconds_total",
             "pages_in_use_hwm", "mem_pressure_events",
             "mem_pressure_episodes")


def run_mem_overhead(engine, vocab, cfg, args, horizon, overlap):
    """``--mem``: the prefix-share shared workload served with memory
    telemetry OFF vs ON at identical settings (prefix cache on for
    both — the cache is what makes the pool attribution interesting),
    INTERLEAVED best-of repeats per the PR-8 methodology so rig drift
    cannot masquerade as telemetry overhead.  The committed section
    carries the overhead fraction, the steady-state prefix-cache
    occupancy fraction (cached pages / pool pages after the workload
    drains — the figure perf_floor reports as an info row), and the
    page-seconds totals.  One extra UNTIMED traced pass dumps the pool
    counter-track Chrome trace to ``--mem-trace-out`` (the CI
    artifact one opens in Perfetto next to the PR-8 spans)."""
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "shared_prefix_len": args.shared_prefix_len,
        "tail_len": args.tail_len,
    }
    prompts, max_new, arrivals = make_prefix_workload(
        vocab, args.requests, args.rate, args.seed,
        args.shared_prefix_len, args.tail_len, share=True)
    # warmup compiles every signature untimed (memory telemetry cannot
    # add any: it is host-only, pinned by test_mem_telemetry.py)
    run_continuous(engine, prompts, max_new, arrivals, cfg,
                   horizon=horizon, overlap=overlap, prefix_cache=True)
    results = {}
    for _ in range(max(1, args.repeats)):
        for label in ("mem_off", "mem_on"):
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap,
                                  prefix_cache=True,
                                  mem_telemetry=(label == "mem_on"))
            best = results.get(label)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                results[label] = cand
    for label, best in results.items():
        section[label] = {k: best[k] for k in _MEM_KEYS if k in best}
    off = results["mem_off"]["tokens_per_sec"]
    on = results["mem_on"]["tokens_per_sec"]
    section["overhead_frac"] = round(1.0 - on / off, 4) if off else None
    # steady-state prefix-cache occupancy: the retired workload's pages
    # left in the radix cache as a fraction of the pool — the capacity
    # figure the quantized-KV work must beat and the autotuner's
    # prefix_cache_pages knob prices against
    section["occupancy_frac"] = round(
        results["mem_on"]["cached_pages"] / cfg["num_pages"], 4)
    if args.mem_trace_out:
        from deepspeed_tpu.serving.trace import SpanTracer
        tracer = SpanTracer(process="bench")
        run_continuous(engine, prompts, max_new, arrivals, cfg,
                       horizon=horizon, overlap=overlap,
                       prefix_cache=True, mem_telemetry=True,
                       tracer=tracer)
        tracer.dump(args.mem_trace_out)
        section["counter_samples"] = sum(
            1 for e in tracer.events if e[0] == "C")
        section["trace_file"] = args.mem_trace_out
    print(json.dumps({
        "metric": "serving_mem_telemetry_overhead_frac",
        "value": section["overhead_frac"], "unit": "frac",
        "extra": {"tokens_per_sec_off": off, "tokens_per_sec_on": on,
                  "occupancy_frac": section["occupancy_frac"],
                  "page_seconds_total":
                      results["mem_on"].get("page_seconds_total")},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "memory", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "memory": section})
    return section


_KVQ_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
             "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "preemptions",
             "page_util_peak", "queue_full_retries")


def run_kv_quant(engine, vocab, cfg, args, horizon, overlap):
    """``--kv-quant``: the quantized-serving-memory scorecard.

    Two legs, both against the fp32 baseline at identical settings:

    * **same_slots** — the standard mixed workload with pool geometry
      UNCHANGED, fp32 vs int8 (vs fp8 where the runtime has it),
      INTERLEAVED best-of repeats (PR-8 methodology).  On the CPU rig
      this prices the dequant work honestly (quantization is a
      capacity lever here, not a speed claim — the TPU kernel path is
      where the bandwidth win cashes out).
    * **capacity** — pool BYTES held constant at the fp32 config's
      footprint while pages and slots grow to what each dtype's
      bytes-per-page affords, served against a high-concurrency
      workload.  The committed ``capacity_ratio`` (pages per byte
      budget, from the same kv_page_bytes arithmetic the allocator
      bills) and the per-dtype preemption/tokens-per-sec rows are what
      perf_floor.py checks; the acceptance test re-proves the ratio
      against live device pools.
    """
    from deepspeed_tpu.ops.quant.kv import fp8_supported
    dtypes = ["float32", "int8"] + (["fp8"] if fp8_supported() else [])
    bpp = {d: engine.kv_page_bytes(cfg["page_size"], kv_dtype=d)
           for d in dtypes}
    budget = cfg["num_pages"] * bpp["float32"]
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "page_bytes": bpp, "pool_bytes_budget": budget,
    }

    # ---- same-slots throughput A/B (geometry fixed, dtype varies)
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    for d in dtypes:                          # warmup: compiles untimed
        run_continuous(engine, prompts, max_new, arrivals, cfg,
                       horizon=horizon, overlap=overlap, kv_dtype=d)
    results = {}
    for _ in range(max(1, args.repeats)):
        for d in dtypes:
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap,
                                  kv_dtype=d)
            best = results.get(d)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                results[d] = cand
    same = {d: {k: r[k] for k in _KVQ_KEYS if k in r}
            for d, r in results.items()}
    f32 = results["float32"]["tokens_per_sec"]
    same["speedup_tokens_per_sec"] = round(
        results["int8"]["tokens_per_sec"] / f32, 3) if f32 else None
    section["same_slots"] = same

    # ---- equal-byte capacity sweep (bytes pinned, pages/slots grow)
    # ONE workload for every dtype — higher concurrency than the pool
    # baseline can hold (capacity is only visible under load that
    # wants it), and byte-identical across dtypes by construction
    cprompts, cmax_new, carrivals = make_workload(
        vocab, args.requests, args.rate * 4, args.seed + 1)
    cap = {}
    for d in dtypes:
        pages_d = int(budget // bpp[d])
        scale = pages_d / cfg["num_pages"]
        cfg_d = dict(cfg, num_pages=pages_d,
                     num_slots=max(cfg["num_slots"],
                                   int(cfg["num_slots"] * scale)))
        run_continuous(engine, cprompts, cmax_new, carrivals, cfg_d,
                       horizon=horizon, overlap=overlap, kv_dtype=d)
        best = None
        for _ in range(max(1, args.repeats)):
            cand = run_continuous(engine, cprompts, cmax_new, carrivals,
                                  cfg_d, horizon=horizon,
                                  overlap=overlap, kv_dtype=d)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                best = cand
        cap[d] = {"num_pages": pages_d, "num_slots": cfg_d["num_slots"],
                  "pool_bytes": pages_d * bpp[d],
                  **{k: best[k] for k in _KVQ_KEYS if k in best}}
    cap["capacity_ratio"] = round(
        cap["int8"]["num_pages"] / cap["float32"]["num_pages"], 3)
    cap["speedup_tokens_per_sec"] = round(
        cap["int8"]["tokens_per_sec"] / cap["float32"]["tokens_per_sec"],
        3) if cap["float32"]["tokens_per_sec"] else None
    section["capacity"] = cap

    print(json.dumps({
        "metric": "serving_kv_quant_capacity_ratio",
        "value": cap["capacity_ratio"], "unit": "x",
        "extra": {"same_slots_speedup": same["speedup_tokens_per_sec"],
                  "capacity_speedup": cap["speedup_tokens_per_sec"],
                  "page_bytes": bpp, "budget": budget},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "kv_quant", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "kv_quant": section})
    return section


# the comm off/on sections report the same per-run schema as tracing
_COMM_KEYS = _TRACE_KEYS


def run_comm_overhead(engine, vocab, cfg, args, horizon, overlap):
    """``--comm``: the standard mixed workload served with comm
    telemetry (HLO ledger capture + recompile watchdog) OFF vs ON at
    identical settings, INTERLEAVED best-of repeats per the PR-8
    methodology so rig drift cannot masquerade as telemetry overhead.
    The ledger analysis compile itself runs AFTER the timed window (the
    production pattern: ``ds_serve`` analyzes at the first heartbeat,
    off the hot path) — what is measured is the per-dispatch capture +
    watchdog cost, which is the cost a serving loop actually pays per
    step.  The committed section carries the overhead fraction, the
    steady-state decode dispatch's wire bytes per step/token and the
    per-axis split; ``--comm-ledger-out`` writes the full
    per-signature ledger JSON (the CI artifact)."""
    from deepspeed_tpu.comm.telemetry import write_ledger_json
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
    }
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    # warmup compiles every signature untimed (comm telemetry cannot
    # add any: capture is host-only, pinned by test_comm_telemetry.py)
    run_continuous(engine, prompts, max_new, arrivals, cfg,
                   horizon=horizon, overlap=overlap)
    results = {}
    comm_sched = None
    for _ in range(max(1, args.repeats)):
        for label in ("comm_off", "comm_on"):
            on = label == "comm_on"
            if not on:
                # a prior on-run leaves engine-level capture armed;
                # the off label must really be the bare loop
                engine.enable_comm_telemetry(False)
                engine.set_compile_watchdog(None)
            holder = []
            cand = run_continuous(engine, prompts, max_new, arrivals,
                                  cfg, horizon=horizon, overlap=overlap,
                                  comm_telemetry=on, sched_out=holder)
            best = results.get(label)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                results[label] = cand
                if on:
                    comm_sched = holder[0]
    engine.set_compile_watchdog(None)
    for label, best in results.items():
        section[label] = {k: best[k] for k in _COMM_KEYS if k in best}
    off = results["comm_off"]["tokens_per_sec"]
    on = results["comm_on"]["tokens_per_sec"]
    section["overhead_frac"] = round(1.0 - on / off, 4) if off else None
    # the static analysis itself, post-measurement: per-signature
    # ledgers + the steady-state decode summary health/gauges carry
    ledgers = comm_sched.comm_ledger()
    s = comm_sched._comm_summary or {}
    section["bytes_per_step"] = s.get("bytes_per_step")
    section["bytes_per_token"] = s.get("bytes_per_token")
    section["collectives_per_step"] = s.get("collectives_per_step")
    section["per_axis"] = s.get("per_axis")
    section["ici_bytes_per_step"] = s.get("ici_bytes")
    section["dcn_bytes_per_step"] = s.get("dcn_bytes")
    section["signatures"] = sorted(ledgers)
    engine.enable_comm_telemetry(False)
    if args.comm_ledger_out:
        write_ledger_json(args.comm_ledger_out, {
            "mesh": comm_sched.mesh_info.get("mesh_shape"),
            "signatures": ledgers})
        section["ledger_file"] = args.comm_ledger_out
    print(json.dumps({
        "metric": "serving_comm_telemetry_overhead_frac",
        "value": section["overhead_frac"], "unit": "frac",
        "extra": {"tokens_per_sec_off": off, "tokens_per_sec_on": on,
                  "bytes_per_step": section["bytes_per_step"],
                  "bytes_per_token": section["bytes_per_token"]},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "comm", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "comm": section})
    return section


_TUNE_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
              "ttft_ms_p99", "tbt_ms_p50", "tpot_ms_p50", "preemptions",
              "page_util_peak", "prefix_hit_rate", "horizon_mean",
              "device_wait_frac")


def run_tune(engine, vocab, cfg, args, horizon, overlap):
    """``--tune``: run the serving autotuner's cost-model-pruned search
    on the prefix-share mix, then bench the DEFAULT config (the bench's
    own serving_config at the swept horizon, prefix cache off — the
    library default) vs the TUNED config at identical settings with
    interleaved best-of repeats.  The committed section is the
    acceptance record: ``tuned_vs_default`` must hold >= 1 within
    noise (the tuner may not regress the default), and the search's
    ``rank_correlation`` is the cost model's honesty figure."""
    from deepspeed_tpu.autotuning.serving import (ServingAutotuner,
                                                  TrafficMix)
    mix = TrafficMix(
        name="prefix_share", requests=args.requests,
        request_rate=args.rate, decode_len=(4, 15),
        shared_prefix_len=args.shared_prefix_len, tail_len=args.tail_len,
        shared_fraction=1.0, seed=args.seed)
    space = {"decode_horizon_steps": [1, 4, 8],
             "prefix_cache": [False, True]}
    # the search starts FROM the bench's own default config (incl. the
    # knobs the space does not search, e.g. max_pages_per_slot), so
    # default vs tuned below differ ONLY in searched knobs — the
    # tuned_vs_default ratio credits the tuner, never an unsearched
    # scheduler default
    base_knobs = dict(cfg, decode_horizon_steps=horizon, overlap=overlap)
    tuner = ServingAutotuner(
        mix, tuning_space=space, measure_top_k=args.tune_top_k,
        repeats=max(1, args.repeats - 1), warmup=1,
        base_knobs=base_knobs)
    tuned = tuner.search(engine)
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "shared_prefix_len": args.shared_prefix_len,
        "tail_len": args.tail_len,
        "mix": mix.to_dict(), "space": space,
        "search": {k: tuned[k] for k in
                   ("overrides", "predicted_tokens_per_sec",
                    "measured_tokens_per_sec", "rank_correlation",
                    "measured", "pruned_infeasible", "pruned_ranked_out",
                    "search_seconds")},
        "tuned_knobs": tuned["knobs"],
        "ds_serve_args": tuned["ds_serve_args"],
    }
    prompts, max_new, arrivals, _ = mix.generate(vocab)
    k = tuned["knobs"]
    runs = {
        "default": dict(cfg=cfg, horizon=horizon, overlap=overlap,
                        prefix_cache=False, prefix_cache_pages=None,
                        spec_decode=None, spec_k=8),
        "tuned": dict(
            cfg={key: k[key] for key in
                 ("num_slots", "num_pages", "page_size",
                  "max_pages_per_slot", "prefill_chunk")},
            horizon=k["decode_horizon_steps"], overlap=k["overlap"],
            prefix_cache=k["prefix_cache"],
            prefix_cache_pages=k["prefix_cache_pages"],
            spec_decode=k["spec_decode"], spec_k=k["spec_k"]),
    }
    results = {}
    for label, r in runs.items():    # warmup compiles untimed
        run_continuous(engine, prompts, max_new, arrivals, r["cfg"],
                       horizon=r["horizon"], overlap=r["overlap"],
                       prefix_cache=r["prefix_cache"],
                       prefix_cache_pages=r["prefix_cache_pages"],
                       spec_decode=r["spec_decode"], spec_k=r["spec_k"])
    # INTERLEAVED best-of (the PR-8 methodology): default and tuned
    # alternate so rig drift cannot masquerade as a tuning win
    for _ in range(max(1, args.repeats)):
        for label, r in runs.items():
            cand = run_continuous(
                engine, prompts, max_new, arrivals, r["cfg"],
                horizon=r["horizon"], overlap=r["overlap"],
                prefix_cache=r["prefix_cache"],
                prefix_cache_pages=r["prefix_cache_pages"],
                spec_decode=r["spec_decode"], spec_k=r["spec_k"])
            best = results.get(label)
            if best is None or cand["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                results[label] = cand
    for label, best in results.items():
        section[label] = {key: best[key] for key in _TUNE_KEYS
                          if key in best}
    off = results["default"]["tokens_per_sec"]
    on = results["tuned"]["tokens_per_sec"]
    section["tuned_vs_default"] = round(on / off, 3) if off else None
    print(json.dumps({
        "metric": "serving_tuned_vs_default_tokens_per_sec",
        "value": section["tuned_vs_default"], "unit": "x",
        "extra": {"tuned_knobs": tuned["overrides"],
                  "rank_correlation": tuned["rank_correlation"],
                  "default_tokens_per_sec": off,
                  "tuned_tokens_per_sec": on},
    }))
    if args.tuned_config_out:
        with open(args.tuned_config_out, "w") as f:
            json.dump(tuned, f, indent=2)
            f.write("\n")
        section["tuned_config_file"] = args.tuned_config_out
    if args.json_out:
        _write_json_out(
            args.json_out, "tuning", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "tuning": section})
    return section


def make_family_workload(vocab, n_requests, rate, seed, n_families,
                         shared_len, tail_len):
    """The cluster-routing workload: ``n_families`` distinct shared
    system prompts, each request = one family's prefix + a distinct
    tail, families interleaved round-robin across arrivals.  With more
    families than replicas, prefix-aware routing pins each family to
    one replica's radix cache (every later member hits), while
    round-robin sprays members across the fleet and pays a cold miss
    per (family, replica) pair — exactly the spread the aggregate hit
    rate measures."""
    rng = np.random.default_rng(seed)
    heads = [rng.integers(0, vocab, shared_len).astype("i4")
             for _ in range(n_families)]
    prompts = []
    for i in range(n_requests):
        tail = rng.integers(0, vocab, tail_len).astype("i4")
        prompts.append(np.concatenate([heads[i % n_families], tail]))
    max_new = [int(rng.integers(4, 16)) for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    return prompts, max_new, arrivals


_CLUSTER_KEYS = ("tokens_per_sec", "wall_s", "tokens",
                 "aggregate_prefix_hit_rate", "aggregate_tokens_reused",
                 "finished", "failed", "shed", "replays", "failovers",
                 "retries", "restarts", "drains")


def run_cluster_once(engine, prompts, max_new, arrivals, cfg, args,
                     horizon, overlap, routing, rolling_restart=False,
                     kill_replica=None, kill_step=6, trace=False):
    from deepspeed_tpu.resilience import faults
    from deepspeed_tpu.serving import ClusterRouter, make_local_fleet

    replicas = make_local_fleet(
        engine, args.cluster, num_slots=cfg["num_slots"],
        num_pages=cfg["num_pages"], page_size=cfg["page_size"],
        max_pages_per_slot=cfg["max_pages_per_slot"],
        prefill_chunk=cfg["prefill_chunk"], decode_horizon_steps=horizon,
        overlap=overlap, prefix_cache=True)
    tracer = flight = None
    if trace and args.cluster_artifacts:
        # the failover pass ships reviewable artifacts: the merged
        # fleet trace plus the flight record the replica death triggers
        from deepspeed_tpu.serving.trace import FlightRecorder, SpanTracer
        tracer = SpanTracer(process="router")
        flight = FlightRecorder(args.cluster_artifacts)
    router = ClusterRouter(replicas, routing=routing, tracer=tracer,
                           flight_recorder=flight)
    inj = None
    if kill_replica is not None:
        inj = faults.FaultInjector(seed=args.seed)
        inj.on("cluster.replica_kill", match={"replica": kill_replica},
               step=kill_step, exc=RuntimeError("bench chaos: kill"))
        faults.install(inj)
    t0 = time.time()
    pending = list(zip(prompts, max_new, arrivals))
    entries = []
    restarted = False
    while True:
        now = time.time() - t0
        while pending and pending[0][2] <= now:
            p, m, _ = pending.pop(0)
            entries.append(router.submit(p, max_new_tokens=m))
        if rolling_restart and not restarted and not pending and \
                len(entries) >= len(prompts):
            # every request is journaled; now restart the whole fleet
            # one replica at a time while the rest keep serving
            router.rolling_restart()
            restarted = True
        work = router.step()
        if not work:
            if not pending:
                break
            time.sleep(max(pending[0][2] - (time.time() - t0), 0.0))
    if inj is not None:
        faults.uninstall()
    wall = time.time() - t0
    toks = sum(len(e.emitted) for e in entries)
    h = router.health()
    out = {k: h[k] for k in
           ("aggregate_prefix_hit_rate", "aggregate_tokens_reused",
            "finished", "failed", "shed", "replays", "failovers",
            "retries", "restarts", "drains")}
    out.update({"wall_s": round(wall, 3), "tokens": toks,
                "tokens_per_sec": round(toks / wall, 2),
                "lost": sum(1 for e in entries
                            if e.state not in ("finished",))})
    return out, router


def run_cluster(engine, vocab, cfg, args, horizon, overlap):
    """Prefix-aware vs round-robin routing over a replica fleet on the
    family-sharded shared-prefix workload, plus a rolling-restart pass
    (drain + restart every replica in sequence) that must finish with
    zero failed requests."""
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "replicas": args.cluster, "families": args.cluster_families,
        "shared_prefix_len": args.shared_prefix_len,
        "tail_len": args.tail_len,
    }
    prompts, max_new, arrivals = make_family_workload(
        vocab, args.requests, args.rate, args.seed, args.cluster_families,
        args.shared_prefix_len, args.tail_len)
    for label, routing in (("round_robin", "round_robin"),
                           ("prefix", "prefix")):
        run_cluster_once(engine, prompts, max_new, arrivals, cfg, args,
                         horizon, overlap, routing)   # untimed warmup
        r = None
        for _ in range(max(1, args.repeats)):
            cand, _ = run_cluster_once(engine, prompts, max_new, arrivals,
                                       cfg, args, horizon, overlap,
                                       routing)
            if r is None or cand["tokens_per_sec"] > r["tokens_per_sec"]:
                r = cand
        section[label] = {k: r[k] for k in _CLUSTER_KEYS if k in r}
    rr, _ = run_cluster_once(engine, prompts, max_new, arrivals, cfg,
                             args, horizon, overlap, "prefix",
                             rolling_restart=True)
    section["rolling_restart"] = {k: rr[k] for k in _CLUSTER_KEYS
                                  if k in rr}
    # failover pass: kill replica0 mid-run under the fault harness —
    # the gating CI job asserts zero lost requests and uploads the
    # journal + fleet health as artifacts
    fo, router = run_cluster_once(engine, prompts, max_new, arrivals,
                                  cfg, args, horizon, overlap, "prefix",
                                  kill_replica="replica0", trace=True)
    section["failover"] = {k: fo[k] for k in
                           tuple(_CLUSTER_KEYS) + ("lost",) if k in fo}
    if args.cluster_artifacts:
        os.makedirs(args.cluster_artifacts, exist_ok=True)
        router.journal.dump(os.path.join(args.cluster_artifacts,
                                         "journal.json"))
        with open(os.path.join(args.cluster_artifacts,
                               "cluster_health.json"), "w") as f:
            json.dump(router.health(), f, indent=2)
            f.write("\n")
        # the traced failover pass's fleet timeline (one process per
        # replica, the killed replica's spans flow-linked to the
        # survivor's replay) rides along with the journal
        router.dump_trace(os.path.join(args.cluster_artifacts,
                                       "fleet_trace.json"))
    if fo["lost"] or fo["failed"]:
        print(f"FAILOVER CHECK FAILED: lost={fo['lost']} "
              f"failed={fo['failed']}", file=sys.stderr)
        raise SystemExit(1)
    if fo["failovers"] != 1:
        print("FAILOVER CHECK: the kill never landed (workload too "
              "short for the armed step?)", file=sys.stderr)
        raise SystemExit(1)
    section["hit_rate_gain"] = round(
        section["prefix"]["aggregate_prefix_hit_rate"] -
        section["round_robin"]["aggregate_prefix_hit_rate"], 4)
    print(json.dumps({
        "metric": "cluster_prefix_vs_round_robin_hit_rate",
        "value": section["hit_rate_gain"], "unit": "delta",
        "extra": {"prefix": section["prefix"],
                  "round_robin": section["round_robin"],
                  "rolling_restart": section["rolling_restart"]},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "cluster", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "cluster": section})
    return section


# ------------------------------------------------- disagg transport

_DISAGG_COUNTERS = ("handoffs", "handoff_transfers", "handoff_bytes",
                    "handoff_chunks", "handoff_aborts", "finished",
                    "failed")

_DISAGG_KEYS = ("tokens_per_sec", "wall_s", "tokens", "ttft_ms_p50",
                "ttft_ms_p99", "handoffs", "handoff_transfers",
                "handoff_bytes", "handoff_chunks", "handoff_transfer_ms",
                "handoff_mb_per_s", "handoff_aborts", "bytes_per_handoff",
                "path_count", "finished", "failed")


def _drive_router(router, prompts, max_new, arrivals):
    """Open-loop arrival replay against a ClusterRouter: a request is
    submitted once its simulated arrival has passed; returns the journal
    entries plus the wall the workload took."""
    t0 = time.time()
    pending = list(zip(prompts, max_new, arrivals))
    entries = []
    while True:
        now = time.time() - t0
        while pending and pending[0][2] <= now:
            p, m, _ = pending.pop(0)
            entries.append(router.submit(p, max_new_tokens=m))
        if not router.step():
            if not pending:
                break
            time.sleep(max(pending[0][2] - (time.time() - t0), 0.0))
    return entries, time.time() - t0


def _settle_wire(router, reps, deadline_s=60.0):
    """Pump the wire fleet until every worker's heartbeat reports a
    fully drained pool: process workers free transferred pages
    asynchronously, so back-to-back passes must not start while the
    previous pass's chains are still being returned."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        router.step()
        up = [r for r in reps if r.state == "up"]
        if up and all((r.last_health or {}).get("free_pages") ==
                      r._cfg["num_pages"] for r in up):
            return
        time.sleep(0.05)
    raise SystemExit("disagg bench: wire pool never drained between "
                     "passes — pages leaked")


def run_disagg_leg(engine, prompts, max_new, arrivals, cfg, args,
                   horizon, overlap, mode, tracer=None):
    """One transport leg: a 1-prefill + 1-decode group on ``mode``
    (shared_pool | device_put | wire), warmed untimed, then
    ``--repeats`` timed passes of the full workload through the SAME
    router — transport counters are delta'd per pass off
    ``router.health()`` so the best pass's DCN-ledger figures match its
    own traffic exactly."""
    from deepspeed_tpu.serving import ClusterRouter
    from deepspeed_tpu.serving.cluster.router import (
        make_disaggregated_group, make_process_disaggregated_group)
    wire = mode == "wire"
    if wire:
        reps = make_process_disaggregated_group(
            num_prefill=1, num_decode=1, model=args.model,
            num_slots=cfg["num_slots"], num_pages=cfg["num_pages"],
            page_size=cfg["page_size"],
            max_pages_per_slot=cfg["max_pages_per_slot"],
            prefill_chunk=cfg["prefill_chunk"], term_grace_s=5.0)
        for rep in reps:
            rep.wait_ready()
    else:
        reps = make_disaggregated_group(
            engine, num_prefill=1, num_decode=1,
            num_pages=cfg["num_pages"], page_size=cfg["page_size"],
            num_slots=cfg["num_slots"],
            max_pages_per_slot=cfg["max_pages_per_slot"],
            prefill_chunk=cfg["prefill_chunk"],
            decode_horizon_steps=horizon, overlap=overlap,
            transport=mode)
    router = ClusterRouter(reps, tracer=tracer)
    try:
        # untimed warmup at FULL concurrency (all arrivals at t=0):
        # compiles every export/import chunk-bucket signature AND every
        # decode batch bucket the timed passes will hit
        _drive_router(router, prompts, max_new,
                      np.zeros(len(prompts)))
        best = None
        for _ in range(max(1, args.repeats)):
            if wire:
                _settle_wire(router, reps)
            h0 = router.health()
            entries, wall = _drive_router(router, prompts, max_new,
                                          arrivals)
            h1 = router.health()
            out = {k: round(h1[k] - h0[k], 3) for k in _DISAGG_COUNTERS}
            ms = h1["handoff_transfer_ms"] - h0["handoff_transfer_ms"]
            ttft = [(e.t_first - e.t_submit) * 1e3 for e in entries
                    if e.t_first is not None]
            toks = sum(len(e.emitted) for e in entries)
            out.update({
                "wall_s": round(wall, 3), "tokens": toks,
                "tokens_per_sec": round(toks / wall, 2),
                "ttft_ms_p50": round(float(np.percentile(ttft, 50)), 3)
                if ttft else None,
                "ttft_ms_p99": round(float(np.percentile(ttft, 99)), 3)
                if ttft else None,
                "handoff_transfer_ms": round(ms, 3),
                "handoff_mb_per_s": round(
                    out["handoff_bytes"] / 1e6 / (ms / 1e3), 3)
                if ms > 0 and out["handoff_bytes"] else 0.0,
                "bytes_per_handoff": round(
                    out["handoff_bytes"] / out["handoff_transfers"], 1)
                if out["handoff_transfers"] else 0.0,
                "path_count": h1["handoff_paths"].get(mode, 0) -
                h0["handoff_paths"].get(mode, 0),
            })
            if best is None or out["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                best = out
        return best, router
    finally:
        if wire:
            for rep in reps:
                rep.die("bench teardown")


def run_disagg(engine, vocab, cfg, args, horizon, overlap):
    """The disaggregated-transport scorecard: the same mixed workload
    through a prefill/decode worker group on each KV transport path —
    ``shared_pool`` (one pool, zero-copy page-id handoff),
    ``device_put`` (separate in-process pools, chunked cross-pool
    transfer), ``wire`` (separate OS processes, length-prefixed binary
    frames on the KV sidecar) — reporting the TTFT tax each hop level
    adds, the DCN-ledger transfer rate, and an exact-bytes check per
    copying path (every transferred chain bills page-aligned prefill
    footprint x the engine's per-page byte cost, nothing more)."""
    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)
    page_bytes = engine.kv_page_bytes(cfg["page_size"])
    chain_pages = sum(-(-len(p) // cfg["page_size"]) for p in prompts)
    section = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "overlap": overlap, "horizon": horizon,
        "kv_page_bytes": page_bytes,
        "chain_pages_per_pass": chain_pages,
        "expected_transfer_bytes": chain_pages * page_bytes,
    }
    tracer = None
    if args.disagg_artifacts:
        # the wire pass ships a reviewable merged fleet timeline: the
        # router's relay spans flow-linked to both workers' transfers
        from deepspeed_tpu.serving.trace import SpanTracer
        tracer = SpanTracer(process="router")
    for mode in ("shared_pool", "device_put", "wire"):
        r, router = run_disagg_leg(
            engine, prompts, max_new, arrivals, cfg, args, horizon,
            overlap, mode, tracer=tracer if mode == "wire" else None)
        section[mode] = {k: r[k] for k in _DISAGG_KEYS if k in r}
        print(json.dumps({
            "metric": f"disagg_{mode}_tokens_per_sec",
            "value": r["tokens_per_sec"], "unit": "tok/s",
            "extra": section[mode],
        }))
        if mode == "wire" and args.disagg_artifacts:
            os.makedirs(args.disagg_artifacts, exist_ok=True)
            router.dump_trace(os.path.join(args.disagg_artifacts,
                                           "disagg_fleet_trace.json"))
            with open(os.path.join(args.disagg_artifacts,
                                   "disagg_health.json"), "w") as f:
                json.dump(router.health(), f, indent=2)
                f.write("\n")
    sp, dp, wp = (section["shared_pool"], section["device_put"],
                  section["wire"])
    # the wire/device_put pair is the apples-to-apples process-boundary
    # price: identical chunked transfer machinery, separate pools on
    # both sides — only the hop differs (in-process device-to-device vs
    # host-staged sidecar frames).  shared_pool rides along as the
    # zero-copy reference, but its single contended pool makes its
    # latency a rig figure, not a transport figure
    section["ttft_penalty_ms_wire_vs_device_put"] = round(
        wp["ttft_ms_p50"] - dp["ttft_ms_p50"], 3)
    section["ttft_ratio_wire_vs_device_put"] = round(
        wp["ttft_ms_p50"] / dp["ttft_ms_p50"], 3) \
        if dp["ttft_ms_p50"] else None
    section["ttft_ratio_wire_vs_shared"] = round(
        wp["ttft_ms_p50"] / sp["ttft_ms_p50"], 3) \
        if sp["ttft_ms_p50"] else None
    section["tokens_per_sec_ratio_wire_vs_device_put"] = round(
        wp["tokens_per_sec"] / dp["tokens_per_sec"], 3) \
        if dp["tokens_per_sec"] else None
    # hard checks, failover-check style: the CI job gates on the
    # transport ledger being EXACT, not plausible.  shared_pool hands
    # chains off by page id — zero copies, so zero transfer rows; the
    # copying paths must bill every request's chain once, to the byte
    want = chain_pages * page_bytes
    for mode in ("shared_pool", "device_put", "wire"):
        leg = section[mode]
        copying = mode != "shared_pool"
        bad = []
        if leg["handoffs"] != args.requests:
            bad.append(f"handoffs={leg['handoffs']} "
                       f"want={args.requests}")
        if leg["handoff_bytes"] != (want if copying else 0):
            bad.append(f"bytes={leg['handoff_bytes']} "
                       f"want={want if copying else 0}")
        if copying and leg["path_count"] != args.requests:
            bad.append(f"path_count={leg['path_count']} "
                       f"want={args.requests}")
        if leg["handoff_aborts"] or leg["failed"]:
            bad.append(f"aborts={leg['handoff_aborts']} "
                       f"failed={leg['failed']}")
        if bad:
            print(f"DISAGG CHECK FAILED ({mode}): {'; '.join(bad)}",
                  file=sys.stderr)
            raise SystemExit(1)
    print(json.dumps({
        "metric": "disagg_ttft_ratio_wire_vs_device_put",
        "value": section["ttft_ratio_wire_vs_device_put"],
        "unit": "ratio",
        "extra": {"wire_mb_per_s": wp["handoff_mb_per_s"],
                  "bytes_per_handoff": wp["bytes_per_handoff"],
                  "expected_transfer_bytes": want},
    }))
    if args.json_out:
        _write_json_out(
            args.json_out, "disagg", section,
            {"model": args.model, "requests": args.requests,
             "rate": args.rate, "serving_config": cfg,
             "overlap": overlap, "disagg": section})
    return section


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-tiny",
                   choices=["gpt2-tiny", "gpt2-small"])
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=4.0,
                   help="Poisson arrival rate (req/s)")
    p.add_argument("--batch", type=int, default=4,
                   help="static-baseline batch size")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--num-pages", type=int, default=64)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-slot", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--horizons", default="1,2,4,8",
                   help="comma-separated fused decode horizons to sweep "
                        "for the continuous system")
    p.add_argument("--no-overlap", action="store_true",
                   help="disable the overlapped host/device loop")
    p.add_argument("--prefix-share", action="store_true",
                   help="run the shared-prefix workload instead of the "
                        "mixed one: N requests sharing a long system "
                        "prompt + distinct tails (and a zero-share "
                        "control), each served with the radix prefix "
                        "cache ON vs OFF")
    p.add_argument("--sampled", action="store_true",
                   help="decoding-policy leg: greedy baseline vs a "
                        "mixed greedy/sampled/penalized policy mix vs "
                        "the mix with a grammar-constrained fraction "
                        "(throughput overhead + compile stability + "
                        "grammar validity)")
    p.add_argument("--spec-decode", action="store_true",
                   help="run the speculative-decoding workload instead: "
                        "repetition-friendly prompts served with the "
                        "n-gram (prompt-lookup) drafter ON vs OFF at "
                        "identical settings — acceptance rate and "
                        "tokens/s speedup reported")
    p.add_argument("--spec-k", type=int, default=8,
                   help="max draft tokens per slot per verify round")
    p.add_argument("--spec-motif-len", type=int, default=8,
                   help="repeated-motif length for --spec-decode prompts")
    p.add_argument("--spec-motif-repeats", type=int, default=3,
                   help="motif repetitions per --spec-decode prompt")
    p.add_argument("--shared-prefix-len", type=int, default=96,
                   help="system-prompt length for --prefix-share")
    p.add_argument("--tail-len", type=int, default=8,
                   help="distinct per-request tail length for "
                        "--prefix-share")
    p.add_argument("--repeats", type=int, default=3,
                   help="--prefix-share timed repetitions per "
                        "configuration; the best run is reported (the "
                        "work is deterministic — repeats only shed "
                        "rig-level clock noise)")
    p.add_argument("--mesh", default=None,
                   help="comma-separated MODELxDATA device-mesh shapes "
                        "to sweep (e.g. '1x8,2x4,4x2'); runs the "
                        "sharded-serving mesh sweep instead of the "
                        "horizon sweep (a 1x1 baseline is always "
                        "included). On CPU, force virtual devices with "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=8 first")
    p.add_argument("--mesh-kernel", action="store_true", default=True,
                   help="(default on) add the kernel-vs-reference "
                        "column to the --mesh sweep: each shape also "
                        "serves through a paged_kernel='force' engine "
                        "— the shard_map'd Pallas paged kernel per kv "
                        "shard (interpret-mode emulation price on CPU; "
                        "the like-for-like kernel leg on real TPU)")
    p.add_argument("--no-mesh-kernel", dest="mesh_kernel",
                   action="store_false",
                   help="skip the kernel column (reference path only)")
    p.add_argument("--long-context", action="store_true",
                   help="run the long-context prefill workload instead: "
                        "a TTFT-vs-prompt-length curve (--lengths) with "
                        "the scheduler's sequence-parallel prefill "
                        "routing ON vs OFF at identical settings, "
                        "served by a rotary llama fixture on a pure "
                        "'sequence' device mesh (force 8 CPU devices "
                        "with XLA_FLAGS=--xla_force_host_platform_"
                        "device_count=8 first); committed as the "
                        "long_context section")
    p.add_argument("--lengths", default="1024,4096,16384,65536",
                   help="comma-separated prompt lengths for "
                        "--long-context")
    p.add_argument("--lc-leg-budget-s", type=float, default=300.0,
                   help="--long-context per-leg time budget: a leg "
                        "whose projected run cost (quadratic scale-up "
                        "of its last measured length) exceeds this is "
                        "skipped with the reason + a labeled power-law "
                        "extrapolation recorded instead of stalling CI "
                        "(the chunked baseline at 64k costs ~1h on a "
                        "1-core rig)")
    p.add_argument("--cluster", type=int, default=0,
                   help="run the cluster-routing workload instead: a "
                        "prefix-aware router over this many in-process "
                        "engine replicas, prefix vs round-robin routing "
                        "on the family-sharded shared-prefix workload, "
                        "plus a rolling-restart pass that must finish "
                        "with zero failed requests")
    p.add_argument("--cluster-families", type=int, default=6,
                   help="distinct shared-prefix families for --cluster")
    p.add_argument("--cluster-artifacts", default=None,
                   help="directory for the --cluster failover pass's "
                        "journal + fleet-health dumps (CI uploads them)")
    p.add_argument("--disagg", action="store_true",
                   help="run the disaggregated-transport workload "
                        "instead: the mixed workload through a "
                        "1-prefill + 1-decode worker group on each KV "
                        "transport path — shared_pool (zero-copy page "
                        "ids), device_put (chunked cross-pool "
                        "transfer), wire (separate OS processes, "
                        "binary KV sidecar frames) — TTFT penalty, "
                        "DCN-ledger MB/s and an exact-bytes check per "
                        "path; committed as the disagg section")
    p.add_argument("--disagg-artifacts", default=None,
                   help="directory for the --disagg wire pass's merged "
                        "fleet trace + health dump (CI uploads them)")
    p.add_argument("--trace", action="store_true",
                   help="run the tracing-overhead workload instead: the "
                        "standard mixed workload with span tracing OFF "
                        "vs ON at identical settings (tokens/s overhead "
                        "reported), dumping one traced repeat's "
                        "per-request span JSON to --trace-out")
    p.add_argument("--trace-out", default="serving_trace.json",
                   help="Chrome-trace JSON destination for --trace")
    p.add_argument("--mem", action="store_true",
                   help="run the memory-telemetry workload instead: the "
                        "prefix-share shared workload with memory "
                        "telemetry OFF vs ON at identical settings "
                        "(tokens/s overhead + steady-state prefix-cache "
                        "occupancy fraction reported), dumping a "
                        "pool-occupancy counter-track Chrome trace to "
                        "--mem-trace-out")
    p.add_argument("--mem-trace-out", default="serving_mem_trace.json",
                   help="counter-track Chrome trace destination for "
                        "--mem (empty string disables the extra traced "
                        "pass)")
    p.add_argument("--kv-quant", action="store_true",
                   help="quantized paged-KV scorecard: same-slots "
                        "fp32-vs-int8(-vs-fp8) throughput A/B with "
                        "interleaved best-of repeats, plus the "
                        "equal-pool-bytes capacity sweep (pages/slots "
                        "grow to what each dtype's bytes-per-page "
                        "affords); committed as the kv_quant section")
    p.add_argument("--comm", action="store_true",
                   help="run the comm-telemetry workload instead: the "
                        "standard mixed workload with the HLO comm "
                        "ledger + recompile watchdog OFF vs ON at "
                        "identical settings (tokens/s overhead + "
                        "bytes-per-step/-token reported), writing the "
                        "per-signature ledger JSON to --comm-ledger-out")
    p.add_argument("--comm-ledger-out", default="serving_comm_ledger.json",
                   help="per-signature comm-ledger JSON destination for "
                        "--comm (empty string disables the artifact)")
    p.add_argument("--tune", action="store_true",
                   help="run the serving-autotuner workload instead: "
                        "cost-model-pruned search over a small knob "
                        "space on the prefix-share mix, then default "
                        "vs tuned config benched at identical settings "
                        "(interleaved best-of repeats); the tuning "
                        "section carries the predicted-vs-measured "
                        "rank correlation")
    p.add_argument("--tune-top-k", type=int, default=4,
                   help="candidates the --tune search measures (the "
                        "cost model ranks the space and prunes the "
                        "rest)")
    p.add_argument("--tuned-config-out", default=None,
                   help="write the --tune winner's tuned-config JSON "
                        "here (what ds_serve --tuned-config loads; CI "
                        "uploads it)")
    p.add_argument("--multi-lora", action="store_true",
                   help="run the multi-tenant multi-LoRA workload: the "
                        "same greedy load base-only vs striped across "
                        "1 and 8 resident adapters through two "
                        "weighted tenants over one page pool (slowdown "
                        "ratio + rank bucket anchor the autotuner's "
                        "cost-model term; the fairness table reports "
                        "the per-tenant page-seconds ledgers)")
    p.add_argument("--lora-adapters", default="1,8",
                   help="comma list of resident-adapter counts the "
                        "--multi-lora leg sweeps (base-only always "
                        "runs as the reference)")
    p.add_argument("--lora-rank", type=int, default=4,
                   help="LoRA rank of the synthetic adapters; decode "
                        "cost scales with the padded power-of-two "
                        "rank bucket")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", default=None)
    args = p.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, gpt2_small, gpt2_tiny

    cfgs = {"gpt2-tiny": gpt2_tiny, "gpt2-small": gpt2_small}
    module = GPT2(cfgs[args.model]())
    vocab = module.cfg.vocab_size
    cfg = {k: getattr(args, k) for k in
           ("num_slots", "num_pages", "page_size", "max_pages_per_slot",
            "prefill_chunk")}

    horizons = [int(h) for h in args.horizons.split(",") if h.strip()]
    overlap = not args.no_overlap

    if args.mesh:
        # builds one engine per mesh shape itself — no default engine
        run_mesh_sweep(module, vocab, cfg, args, max(horizons), overlap)
        return

    if args.long_context:
        # builds its own rotary-llama engine on a sequence mesh — the
        # learned-position GPT-2 fixtures cap out far below 64k
        run_long_context(cfg, args, max(horizons), overlap)
        return

    engine = deepspeed_tpu.init_inference(
        module, dtype="float32", kv_cache_dtype="float32",
        max_out_tokens=args.max_pages_per_slot * args.page_size)
    engine.init_params()

    prompts, max_new, arrivals = make_workload(
        vocab, args.requests, args.rate, args.seed)

    if args.cluster:
        run_cluster(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.disagg:
        run_disagg(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.prefix_share:
        run_prefix_share(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.spec_decode:
        run_spec_decode(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.sampled:
        run_sampled(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.tune:
        run_tune(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.trace:
        run_trace_overhead(engine, vocab, cfg, args, max(horizons),
                           overlap)
        return

    if args.mem:
        run_mem_overhead(engine, vocab, cfg, args, max(horizons),
                         overlap)
        return

    if args.comm:
        run_comm_overhead(engine, vocab, cfg, args, max(horizons),
                          overlap)
        return

    if args.kv_quant:
        run_kv_quant(engine, vocab, cfg, args, max(horizons), overlap)
        return

    if args.multi_lora:
        run_multi_lora(engine, vocab, cfg, args, max(horizons), overlap)
        return

    # warmup: compile every signature both systems will hit (the serving
    # primitives at every swept horizon's bucket set, plus generate() at
    # each static batch/length bucket)
    for h in horizons:
        run_continuous(engine, prompts[:4], max_new[:4], np.zeros(4), cfg,
                       horizon=h, overlap=overlap)
    run_static(engine, prompts, [1] * len(prompts), np.zeros(len(prompts)),
               args.batch)

    sweep = {}
    for h in horizons:
        r = run_continuous(engine, prompts, max_new, arrivals, cfg,
                           horizon=h, overlap=overlap)
        sweep[str(h)] = {k: r[k] for k in
                         ("tokens_per_sec", "wall_s", "tokens",
                          "ttft_ms_p50", "ttft_ms_p99",
                          "tbt_ms_p50", "tbt_ms_p99",
                          "tpot_ms_p50", "tpot_ms_p99",
                          "horizon_mean", "device_wait_frac",
                          "preemptions") if k in r}
        sweep[str(h)]["full"] = r
    best_h = max(sweep, key=lambda h: sweep[h]["tokens_per_sec"])
    cont = sweep[best_h]["full"]
    stat = run_static(engine, prompts, max_new, arrivals, args.batch)

    results = {
        "model": args.model, "requests": args.requests, "rate": args.rate,
        "serving_config": cfg, "static_batch": args.batch,
        "overlap": overlap,
        "horizon_sweep": {h: {k: v for k, v in r.items() if k != "full"}
                          for h, r in sweep.items()},
        "best_horizon": int(best_h),
        "continuous": cont, "static": stat,
        "speedup": round(cont["tokens_per_sec"] / stat["tokens_per_sec"], 3)
        if stat["tokens_per_sec"] else None,
        "speedup_best_h_vs_h1": round(
            cont["tokens_per_sec"] / sweep["1"]["tokens_per_sec"], 3)
        if "1" in sweep and sweep["1"]["tokens_per_sec"] else None,
    }
    for h in sorted(sweep, key=int):
        print(json.dumps({
            "metric": "serving_continuous_tokens_per_sec",
            "value": sweep[h]["tokens_per_sec"], "unit": "tok/s",
            "extra": {"horizon": int(h),
                      **{k: v for k, v in sweep[h].items() if k != "full"}},
        }))
    print(json.dumps({
        "metric": "serving_static_tokens_per_sec",
        "value": stat["tokens_per_sec"], "unit": "tok/s", "extra": stat,
    }))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
