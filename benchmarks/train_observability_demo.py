"""Training-observability artifact driver (CI: `train-observability`).

Runs the canonical fault-injected scenario end to end on the tiny
regression fixture — periodic checkpointing, an injected hard crash at
step 5 (a preemption with no grace), a second *incarnation* that
resumes from the last intact tag and finishes, plus one injected
straggler step — and writes the three artifacts an operator would pull
after a real incident:

* ``train_trace.json`` — the merged cross-incarnation Chrome/Perfetto
  trace (both processes share the run id; open at
  https://ui.perfetto.dev),
* ``flight_*.json`` — the flight-recorder dumps the straggler triggered,
* ``goodput_ledger.json`` — the cumulative goodput partition +
  throughput gauges + the Prometheus exposition.

Exits nonzero if the ledger fails its own contract (categories must
partition 100% of wall time; recompute and checkpoint-stall must be
separately nonzero after a crash+resume), so the CI job is a real
check, not just an artifact producer.

Usage:
  python benchmarks/train_observability_demo.py --out train-obs-artifacts
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="train-obs-artifacts")
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    import deepspeed_tpu
    from deepspeed_tpu.resilience import faults
    from deepspeed_tpu.resilience.supervisor import ResilientTrainer
    from deepspeed_tpu.tracing import FlightRecorder, SpanTracer

    from tests.unit.simple_model import (SimpleModel,
                                         random_regression_data,
                                         simple_loss_fn)

    def make_engine():
        import jax
        n_dev = len(jax.devices())
        model = SimpleModel()
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "mesh": {"data": n_dev}, "steps_per_print": 1000}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, loss_fn=simple_loss_fn(model))
        return engine

    def batch_fn(step):
        return random_regression_data(n=32, seed=step)

    os.makedirs(args.out, exist_ok=True)
    work = tempfile.mkdtemp(prefix="ds_train_obs_")
    run_dir = os.path.join(work, "run")
    flight_dir = os.path.join(args.out, "flight")

    # ---- incarnation 1: periodic saves, hard crash at step 5
    sup1 = ResilientTrainer(make_engine(), run_dir, save_interval=3,
                            tracer=SpanTracer(process="train"),
                            flight_recorder=FlightRecorder(flight_dir),
                            gauge_interval=2)
    inj = faults.FaultInjector(seed=0)
    inj.on("train.step", step=5, exc=RuntimeError("simulated hard crash"))
    try:
        with faults.injected(inj):
            sup1.train(args.steps, batch_fn=batch_fn)
        print("ERROR: the injected crash did not fire", file=sys.stderr)
        return 1
    except RuntimeError as e:
        print(f"incarnation 1 crashed as injected: {e}")

    # ---- incarnation 2: resume + finish, with one straggler step
    sup2 = ResilientTrainer(make_engine(), run_dir, save_interval=3,
                            tracer=SpanTracer(process="train"),
                            flight_recorder=FlightRecorder(flight_dir),
                            gauge_interval=2, straggler_factor=3.0)
    assert sup2.run_id == sup1.run_id, "run identity must survive"
    tag = sup2.resume(example_batch=batch_fn(0))
    print(f"incarnation 2 resumed from {tag}")
    inj2 = faults.FaultInjector(seed=0)
    inj2.on("train.step", step=args.steps - 2,
            action=faults.sleep_s(0.5))
    with faults.injected(inj2):
        rep = sup2.train(args.steps, batch_fn=batch_fn)

    # ---- artifacts
    shutil.copy(os.path.join(run_dir, "trace", "train_trace.json"),
                os.path.join(args.out, "train_trace.json"))
    ledger_doc = {
        "run_id": rep.run_id,
        "incarnations": rep.incarnation,
        "status": rep.status,
        "resumed_from": tag,
        "stragglers": rep.stragglers,
        "mfu": rep.mfu,
        "tokens_per_s": rep.tokens_per_s,
        "ledger": rep.ledger,
        "prometheus": sup2.prometheus_text(),
    }
    with open(os.path.join(args.out, "goodput_ledger.json"), "w") as f:
        json.dump(ledger_doc, f, indent=2)
        f.write("\n")

    led = rep.ledger
    print(f"\nrun {rep.run_id}: {rep.incarnation} incarnations, "
          f"wall {led['wall_s']:.2f}s")
    width = max(len(c) for c in led["seconds"])
    for cat, sec in sorted(led["seconds"].items(),
                           key=lambda kv: -kv[1]):
        frac = led["fractions"][cat]
        print(f"  {cat:{width}s} {sec:8.3f}s  {frac:6.1%}")

    # ---- the contract this job gates on
    problems = []
    if abs(sum(led["fractions"].values()) - 1.0) > 1e-6:
        problems.append("fractions do not sum to 1")
    if led["seconds"]["recompute"] <= 0:
        problems.append("recompute is zero after a crash+resume")
    if led["seconds"]["checkpoint_stall"] <= 0:
        problems.append("checkpoint_stall is zero despite saves")
    if rep.status != "completed":
        problems.append(f"run did not complete: {rep.status}")
    if rep.stragglers < 1:
        problems.append("the injected straggler was not detected")
    # FlightRecorder creates its dir lazily on the first dump — its
    # absence IS the "no dumps" diagnosis, not a crash
    if not os.path.isdir(flight_dir) or not os.listdir(flight_dir):
        problems.append("no flight-recorder dumps")
    if problems:
        print("FAIL: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(f"\nOK — artifacts in {args.out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
