"""ZeRO-Offload scale proof: train a model whose fp32 Adam state exceeds
one chip's HBM.

Reference claim being matched: ZeRO-Offload trains 13B on a single
V100-32GB (docs/_posts/2020-09-09-ZeRO-Offload.md:9) by keeping fp32
master params + moments in host RAM with CPU-Adam. Here: a ~2B-param GPT
on one 16GB v5e — Adam state alone is ~24GB fp32, impossible on-chip; the
chip holds only the bf16 compute copy + grads.

Prints one JSON line with tokens/s and the state sizes.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    scale = os.environ.get("DS_OFFLOAD_SCALE", "small")
    if on_tpu and scale == "large":
        # ~2B params: fp32 Adam state = ~24GB, impossible in 16GB HBM.
        # Needs a real TPU-VM host link (GB/s DMA); dev tunnels that relay
        # host<->device traffic at MB/s should use the default size.
        cfg = GPTConfig(vocab_size=50257, hidden_size=2304, num_layers=30,
                        num_heads=24, max_seq_len=512, dtype=jnp.bfloat16,
                        remat=True)
        batch, seq, steps = 2, 512, 3
    elif on_tpu:
        cfg = GPTConfig(vocab_size=50257, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512, dtype=jnp.bfloat16)
        batch, seq, steps = 4, 512, 3
    else:  # smoke mode off-TPU
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, dtype=jnp.bfloat16)
        batch, seq, steps = 2, 64, 2

    model = GPT2(cfg)
    config = {
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}},
        "mesh": {"data": 1},
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    batch_data = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)}

    losses = []
    t0 = None
    for i in range(steps + 1):
        if i == 1:
            t0 = time.time()   # step 0 pays compile
        loss = engine.forward(batch_data)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    dt = time.time() - t0

    n_params = sum(m.size for m in engine._offload.master)
    state_gb = n_params * 4 * 3 / 1e9      # fp32 master + m + v
    device_gb = n_params * 2 / 1e9         # bf16 compute copy
    print(json.dumps({
        "metric": "zero_offload_train_tokens_per_sec",
        "value": round(batch * seq * steps / dt, 1),
        "unit": "tokens/s",
        "extra": {
            "n_params_b": round(n_params / 1e9, 3),
            "host_optimizer_state_gb": round(state_gb, 1),
            "device_param_gb": round(device_gb, 1),
            "losses": [round(l, 3) for l in losses],
            "platform": jax.devices()[0].platform,
        },
    }))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], "no learning signal"


if __name__ == "__main__":
    main()
