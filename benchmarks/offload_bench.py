"""ZeRO-Offload / ZeRO-Infinity scale proof + overlap measurement.

Reference claims being matched:
  - ZeRO-Offload trains 13B on a single V100-32GB
    (docs/_posts/2020-09-09-ZeRO-Offload.md:9) by keeping fp32 master
    params + moments in host RAM with CPU-Adam. Here: a ~2B-param GPT on
    one 16GB v5e — fp32 Adam state alone is ~24GB, impossible on-chip.
  - ZeRO-3 (param) offload trains models whose *parameters* also exceed
    HBM (docs/_posts/2021-03-08-zero3-offload.md:75, 40B on one V100) by
    streaming them from pinned host memory per use
    (runtime/zero/stage3.py:445-480).

Modes (one JSON line each; DS_OFFLOAD_MODE=opt|param|nvme|both|all):
  opt    — optimizer-state offload only (ZeRO-2 + cpu Adam)
  param  — + ZeRO-3 parameter offload: at-rest params in pinned host
           memory, streamed to HBM per step; between steps the chip
           holds no parameters. On TPU the line includes the measured
           HBM peak and asserts headroom (peak < params+opt state).
  nvme   — ZeRO-Infinity parameter tier: at-rest params, fp32 masters,
           grad accumulators and moments all in NVMe files
           (runtime/zero/offload.py NvmeParamTier); host RAM holds a
           couple of leaf buffers (param_tier_peak_buffer_bytes proves
           it) and nvme_prefetch_overlap shows the double-buffered
           leaf-state reads hiding behind the host Adam sweep.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))




def run_mode(mode):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    scale = os.environ.get("DS_OFFLOAD_SCALE", "small")
    if on_tpu and scale == "large":
        # ~2B params: fp32 Adam state = ~24GB, impossible in 16GB HBM.
        # Needs a real TPU-VM host link (GB/s DMA); dev tunnels that relay
        # host<->device traffic at MB/s should use the default size.
        cfg = GPTConfig(vocab_size=50257, hidden_size=2304, num_layers=30,
                        num_heads=24, max_seq_len=512, dtype=jnp.bfloat16,
                        remat=True, scan_layers=(mode == "param"))
        batch, seq, steps = 2, 512, 3
    elif on_tpu:
        cfg = GPTConfig(vocab_size=50257, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512, dtype=jnp.bfloat16,
                        scan_layers=(mode == "param"))
        batch, seq, steps = 4, 512, 3
    else:  # smoke mode off-TPU
        cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128, dtype=jnp.bfloat16,
                        scan_layers=(mode == "param"))
        batch, seq, steps = 2, 64, 2

    if mode == "param":
        zero = {"stage": 3,
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"}}
    elif mode == "nvme":
        nvme_dir = os.environ.get("DS_NVME_PATH", "/tmp/ds_nvme_bench")
        zero = {"stage": 3,
                "offload_param": {"device": "nvme",
                                  "nvme_path": nvme_dir},
                "offload_optimizer": {"device": "nvme",
                                      "nvme_path": nvme_dir}}
    else:
        zero = {"stage": 2, "offload_optimizer": {"device": "cpu"}}

    model = GPT2(cfg)
    config = {
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": zero,
        "mesh": {"data": 1},
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    batch_data = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)}

    # host<->device link bandwidth probe: pins whether a slow result is
    # the rig's link or missing overlap (dev tunnels relay DMA at MB/s;
    # a real TPU-VM host moves GB/s)
    probe = np.zeros(64 << 20, np.uint8)    # 64 MB
    dev = jax.device_put(probe)
    jax.block_until_ready(dev)
    t0 = time.time()
    jax.block_until_ready(jax.device_put(probe))
    h2d_gbps = probe.nbytes / (time.time() - t0) / 1e9
    t0 = time.time()
    np.asarray(dev)
    d2h_gbps = probe.nbytes / (time.time() - t0) / 1e9

    from deepspeed_tpu.utils.memory import device_memory_stats
    losses = []
    t0 = None
    for i in range(steps + 1):
        if i == 1:
            t0 = time.time()   # step 0 pays compile
            engine.offload_phase_stats()   # drop compile-step phases
        loss = engine.forward(batch_data)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    dt = time.time() - t0
    phases = engine.offload_phase_stats()
    # allocator high-water mark, which covers WITHIN-step residency
    # (sampling bytes_in_use after each step would only see between-step
    # state, where the streamed params are already freed)
    hbm_peak = device_memory_stats().get("peak_bytes_in_use") or None

    n_params = sum(engine._offload.sizes)
    state_gb = n_params * 4 * 3 / 1e9      # fp32 master + m + v
    device_gb = n_params * 2 / 1e9         # bf16 compute copy
    extra = {
        "n_params_b": round(n_params / 1e9, 3),
        "host_optimizer_state_gb": round(state_gb, 1),
        "device_param_gb": round(device_gb, 1),
        "losses": [round(l, 3) for l in losses],
        "platform": jax.devices()[0].platform,
        # per-phase breakdown over the timed steps (VERDICT r3 weak #2):
        # d2h_accum_s = grad D2H + fp32 accumulate on the worker thread,
        # join_stall_s = the part of that NOT hidden behind device
        # compute, host_adam_s = fused host Adam, h2d_emit_s = async
        # param-return dispatch. overlap_fraction = 1 - stall/d2h.
        "phases": phases,
        "step_wall_s": round(dt / steps, 3),
        "link_h2d_gbps": round(h2d_gbps, 3),
        "link_d2h_gbps": round(d2h_gbps, 3),
        # the breakdown pins WHY a slow result is slow: when
        # d2h_accum_s/steps ~ grad_bytes/link_d2h_gbps the rig's relayed
        # host link is the wall (dev tunnels measure ~0.01 GB/s vs a
        # TPU-VM host's ~10 GB/s: the same phases predict sub-second D2H
        # there, fully hidden by the worker-thread pipeline at gas>1);
        # only when join_stall << d2h_accum with a fast link would
        # missing overlap be the story.
        "analysis": "step ~= max(device_compute, d2h_accum) + host_adam "
                    "+ h2d; see link_d2h_gbps",
    }
    if mode == "nvme":
        # RAM-residency proof: the sweep never held a model-sized buffer
        # (peak = ~2 leaves' (master, acc) pairs, bounded by the largest
        # leaf, NOT the model)
        extra["ram_bound_proof"] = {
            "model_fp32_bytes": n_params * 4,
            "peak_leaf_buffer_bytes":
                phases.get("param_tier_peak_buffer_bytes"),
        }
    if hbm_peak is not None:
        extra["hbm_peak_gb"] = round(hbm_peak / 1e9, 2)
        if mode == "param":
            # headroom proof: the chip never held params + optimizer
            # state; at-rest params live on the host
            assert hbm_peak < (n_params * 2 + n_params * 12), \
                (hbm_peak, n_params)
    print(json.dumps({
        "metric": f"zero_offload_{mode}_train_tokens_per_sec",
        "value": round(batch * seq * steps / dt, 1),
        "unit": "tokens/s",
        "extra": extra,
    }))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], "no learning signal"


def main():
    mode = os.environ.get("DS_OFFLOAD_MODE", "both")
    modes = {"both": ["opt", "param"],
             "all": ["opt", "param", "nvme"]}.get(mode, [mode])
    for m in modes:
        run_mode(m)


if __name__ == "__main__":
    main()
