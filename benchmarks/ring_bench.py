"""Ring-attention long-context benchmark (the capability claim of
SURVEY.md §5.7: context length scales with the `sequence` mesh axis).

Compares, at a given total sequence length:
  * full flash attention on one device (memory O(L), compute O(L^2));
  * ring attention with L sharded over the sequence axis (per-device
    memory O(L/P); k/v chunks hop the ring in input dtype).

On the 1-chip TPU env the ring degenerates (P=1), so the headline row is
the single-chip flash at 32k — the ring rows need a multi-device mesh
(CI runs the 8-device virtual CPU mesh at reduced size; a pod runs the
real thing over ICI).

Usage: python benchmarks/ring_bench.py [--seq 32768] [--heads 4]
       [--dim 64] [--cpu-devices 0] [--json out.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fence(x):
    import jax
    import jax.numpy as jnp
    return float(jax.device_get(jnp.sum(x.astype(jnp.float32))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=32768)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="force an N-device virtual CPU mesh")
    p.add_argument("--sparse-seqs", default="8192,16384,32768",
                   help="sequence lengths for the sparse-vs-dense sweep "
                        "('' disables)")
    p.add_argument("--json", default=None)
    args = p.parse_args()

    if args.cpu_devices:
        # before jax initializes: jax<0.5 has no jax_num_cpu_devices
        # option, only the XLA flag
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = flags + \
                f" --xla_force_host_platform_device_count={args.cpu_devices}"
    import jax
    if args.cpu_devices:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except AttributeError:
            pass   # jax<0.5: XLA_FLAGS above already set the count
    import jax.numpy as jnp
    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.ops.attention import flash_attention
    from deepspeed_tpu.ops.attention.ring import ring_attention_sharded
    from deepspeed_tpu.parallel.topology import make_mesh
    from deepspeed_tpu.runtime.config import MeshConfig

    n_dev = len(jax.devices())
    L, h, d = args.seq, args.heads, args.dim
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(1, L, h, d)) * 0.3, dtype)
    q, k, v = mk(), mk(), mk()
    results = []

    def bench(f, *xs, n1=10 * args.trials, n2=60 * args.trials):
        """Chained two-point measurement: the kernel runs inside ONE
        jitted fori_loop per window (iteration i+1 consumes iteration
        i's output), so per-dispatch overhead — ~6 ms through a relayed
        rig, enough to swamp a sub-ms sparse kernel if each call were
        its own dispatch — amortizes over the whole chain; the n2-n1
        difference then cancels the remaining per-window constant."""
        import functools

        @functools.partial(jax.jit, static_argnums=(1,))
        def run(x, n):
            return jax.lax.fori_loop(
                0, n, lambda i, x: f(x, *xs[1:]), x)

        fence(run(xs[0], n1))
        fence(run(xs[0], n2))

        def window(n):
            t0 = time.time()
            out = run(xs[0], n)
            fence(out)
            return time.time() - t0
        ds = []
        for _ in range(3):
            t1, t2 = window(n1), window(n2)
            ds.append((t2 - t1) / (n2 - n1))
        return float(np.median(ds)) * 1e3

    full = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    t_full = bench(full, q, k, v)
    row = {"metric": "full_flash_attention", "seq": L, "heads": h,
           "latency_ms": round(t_full, 2), "n_devices": 1,
           "platform": jax.default_backend()}
    results.append(row)
    print(json.dumps(row))

    if n_dev > 1:
        mesh = make_mesh(MeshConfig(sequence=n_dev))
        dist.set_mesh(mesh)
        ring = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, causal=True))
        t_ring = bench(ring, q, k, v)
        err = float(jnp.max(jnp.abs(
            (ring(q, k, v) - full(q, k, v)).astype(jnp.float32))))
        row = {"metric": "ring_attention", "seq": L, "heads": h,
               "latency_ms": round(t_ring, 2), "n_devices": n_dev,
               "chunk": L // n_dev, "max_err_vs_full": round(err, 5),
               "platform": jax.default_backend()}
        # static HLO comm ledger of the compiled ring kernel: the k/v
        # chunks really hopping the sequence axis, in the same
        # (op, bytes, algbw/busbw) vocabulary as run_all.py and the
        # runtime serving ledger — bench and telemetry numbers are
        # directly comparable
        from deepspeed_tpu.profiling.comm_ledger import ledger_for
        led = ledger_for(ring, q, k, v, mesh=mesh)
        t_s = max(t_ring * 1e-3, 1e-9)
        row["comm"] = {"bytes": led["bytes"],
                       "wire_bytes": led["wire_bytes"],
                       "per_axis": led["per_axis"]}
        results.append(row)
        print(json.dumps(row))
        for op, d in sorted(led["per_op"].items()):
            crow = {"metric": "ring_comm", "op": op,
                    "bytes": d["bytes"], "wire_bytes": d["wire_bytes"],
                    "count": d["count"],
                    "latency_ms": round(t_ring, 2),
                    "algbw_gbps": round(d["bytes"] / t_s / 1e9, 3),
                    "busbw_gbps": round(d["wire_bytes"] / t_s / 1e9, 3),
                    "n": n_dev, "axis": "sequence"}
            results.append(crow)
            print(json.dumps(crow))

    # ---- block-sparse vs dense at long sequence (the measured speedup
    # backing BASELINE.md's sparse-attention row: the reference claims
    # up to ~6x over dense at long seq,
    # docs/_posts/2020-09-09-sparse-attention.md). Grid steps exist only
    # for active blocks, so latency should scale ~ layout density.
    if args.sparse_seqs:
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, BSLongformerSparsityConfig)
        for L2 in [int(s) for s in args.sparse_seqs.split(",") if s]:
            qs = jnp.asarray(rng.normal(size=(1, L2, h, d)) * 0.3, dtype)
            dense = jax.jit(
                lambda q, k, v: flash_attention(q, k, v, causal=True))
            t_dense = bench(dense, qs, qs, qs)
            row = {"metric": "dense_flash", "seq": L2,
                   "latency_ms": round(t_dense, 2),
                   "tokens_per_sec": round(L2 / t_dense * 1e3, 1)}
            results.append(row)
            print(json.dumps(row))
            # two granularities: block 128 keeps the reference patterns'
            # fine resolution (per-step overhead bound on TPU); block
            # 512 is the MXU-native tile — comparable token coverage
            # (~1.5k-token window vs HF BigBird's ~512), and the grid
            # steps are big enough to run at the layout's density
            for name, cfg in [
                ("bigbird", BigBirdSparsityConfig(
                    num_heads=h, block=128, num_random_blocks=1,
                    num_sliding_window_blocks=3, num_global_blocks=1)),
                ("bigbird_b512", BigBirdSparsityConfig(
                    num_heads=h, block=512, num_random_blocks=1,
                    num_sliding_window_blocks=1, num_global_blocks=1)),
                ("longformer", BSLongformerSparsityConfig(
                    num_heads=h, block=128,
                    num_sliding_window_blocks=3,
                    global_block_indices=[0])),
                ("longformer_b512", BSLongformerSparsityConfig(
                    num_heads=h, block=512,
                    num_sliding_window_blocks=1,
                    global_block_indices=[0])),
            ]:
                # the kernel runs causal=True, which trils the layout:
                # the EXECUTED density (and so the admissible speedup)
                # is the lower-triangle's
                layout = np.tril(np.asarray(cfg.make_layout(L2)))
                density = float(layout.mean())
                sp = jax.jit(lambda q, k, v, c=cfg: flash_attention(
                    q, k, v, causal=True, sparsity_config=c))
                t_sp = bench(sp, qs, qs, qs)
                row = {"metric": f"sparse_flash_{name}", "seq": L2,
                       "latency_ms": round(t_sp, 2),
                       "tokens_per_sec": round(L2 / t_sp * 1e3, 1),
                       "layout_density": round(density, 4),
                       "speedup_vs_dense": round(t_dense / t_sp, 2),
                       # causal dense does ~density-0.5 of the square;
                       # the layout admits at most 0.5/density speedup —
                       # how close the kernel gets IS its efficiency
                       "density_ceiling": round(0.5 / density, 2)}
                results.append(row)
                print(json.dumps(row))

    if args.json:
        # comm-ledger schema envelope; committed rounds survive re-runs
        # under previous_committed
        from deepspeed_tpu.comm.telemetry import write_ledger_json
        write_ledger_json(args.json, {"results": results})


if __name__ == "__main__":
    main()
