"""Async file-IO sweep (reference ``csrc/aio/py_test/aio_bench_perf_sweep.py``
— the NVMe tier's perf harness behind ZeRO-Infinity).

Sweeps (block_size, thread_count, o_direct) over the native AIO
handle's read and write paths and reports GB/s per configuration as
bench-style JSON lines. Without --o-direct the numbers include the page
cache (useful for the double-buffered optimizer-swap pattern, where the
cache is an asset); pass --o-direct for raw device throughput like the
reference sweep.

Usage: python benchmarks/aio_bench.py [--dir /tmp] [--mb 64]
       [--block-sizes 262144,1048576] [--threads 1,4] [--o-direct]
       [--json out.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dir", default="/tmp")
    p.add_argument("--mb", type=int, default=64, help="file size in MiB")
    p.add_argument("--block-sizes", default="262144,1048576")
    p.add_argument("--threads", default="1,4")
    p.add_argument("--o-direct", action="store_true")
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--json", default=None)
    args = p.parse_args()

    from deepspeed_tpu.ops.aio import AioHandle

    nbytes = args.mb * (1 << 20)
    data = np.random.default_rng(0).integers(
        0, 255, nbytes, dtype=np.uint8)
    path = os.path.join(args.dir, "aio_bench.bin")
    results = []
    try:
        for bs in (int(x) for x in args.block_sizes.split(",")):
            for th in (int(x) for x in args.threads.split(",")):
                h = AioHandle(block_size=bs, thread_count=th,
                              o_direct=args.o_direct)
                # write sweep
                t_w = []
                for _ in range(args.trials):
                    t0 = time.time()
                    h.async_pwrite(data, path)
                    h.wait()
                    t_w.append(time.time() - t0)
                # read sweep
                buf = np.empty(nbytes, np.uint8)
                t_r = []
                for _ in range(args.trials):
                    t0 = time.time()
                    h.async_pread(buf, path)
                    h.wait()
                    t_r.append(time.time() - t0)
                assert (buf == data).all(), "aio read corruption"
                row = {
                    "block_size": bs, "threads": th,
                    "o_direct": bool(args.o_direct), "file_mb": args.mb,
                    "write_gbps": round(nbytes / min(t_w) / 1e9, 3),
                    "read_gbps": round(nbytes / min(t_r) / 1e9, 3),
                }
                results.append(row)
                print(json.dumps(row))
    finally:
        if os.path.exists(path):
            os.remove(path)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
