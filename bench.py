"""Benchmark: GPT-2-small training throughput on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md): the reference's ZeRO-3 Offload sustained
50 TFlops/GPU on V100 = 40% MFU (50/125 fp16 peak). vs_baseline is
our_MFU / 0.40, so 1.0 == matching the reference's best published
utilization on its own hardware class.
"""

import json
import os
import time

import numpy as np

def guess_peak(device):
    # the per-chip peak table lives with the profiler now (the live MFU
    # gauge in resilience/supervisor.py reads the same numbers)
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        peak_flops_per_device)
    return peak_flops_per_device(device)


def run_config(gas, batch, seq, n_dev):
    """Train GPT-2-small for a timed window; returns (tokens/s, loss).
    gas>1 uses the engine's scan-fused window (one dispatch per
    optimizer step), with micro = batch // gas so tokens/step is the
    same in every configuration."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    micro = batch // gas
    cfg = GPTConfig(vocab_size=50257, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=seq, dtype=jnp.bfloat16)
    model = GPT2(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4,
                                                  "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": n_dev},
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    micros = [{"input_ids": rng.integers(
        0, cfg.vocab_size,
        size=(micro * n_dev, seq)).astype(np.int32)} for _ in range(gas)]

    # Both configs drive the scan-over-steps fused loop (train_loop):
    # `span` complete optimizer steps (fused gas windows at gas>1) per
    # dispatch. Identical math to per-step forward/backward/step
    # (tests/unit/test_engine.py asserts the trajectories match); it
    # amortizes per-dispatch host overhead, which on this relayed rig is
    # ~6ms/dispatch (a local TPU VM pays ~100us).
    span = 5
    micros_rep = micros * span   # span whole windows per dispatch

    def step():
        return engine.train_loop(micros_rep, sync=False)

    def fence():
        # A host transfer of a value derived from the params cannot complete
        # before every prior step: a true fence even through async device
        # relays where block_until_ready returns early.
        leaf = jax.tree.leaves(engine.state.params)[0]
        return float(jax.device_get(jnp.sum(leaf)))

    # warmup (compile); collect losses so the loss-after-23-steps stat
    # stays comparable with earlier rounds' 23-dispatch protocol
    all_losses = []
    for _ in range(3):
        all_losses.append(step())
    fence()

    n_calls = 20 if on_tpu else 3
    n_steps = n_calls * span
    t0 = time.time()
    for _ in range(n_calls):
        all_losses.append(step())
    fence()
    dt = time.time() - t0
    loss23 = np.concatenate([jax.device_get(l) for l in all_losses])[22] \
        if on_tpu else float(jax.device_get(all_losses[-1][-1]))

    profile = None
    if gas == 1 and os.environ.get("DS_BENCH_PROFILE"):
        # per-module measured breakdown on THE SAME engine/config the
        # numbers above came from (engine.module_profile): the full
        # table goes to stderr, the top HBM-traffic consumers ride the
        # JSON line so a step-time regression carries its own diagnosis
        import sys
        from deepspeed_tpu.profiling.module_profiler import (
            top_traffic_consumers)
        records, table = engine.module_profile(micros[0], depth=3)
        print(table, file=sys.stderr)
        profile = [
            {k: (round(v, 3) if isinstance(v, float) else v)
             for k, v in t.items()}
            for t in top_traffic_consumers(records)]

    tokens_per_step = batch * n_dev * seq
    tokens_per_sec = tokens_per_step * n_steps / dt
    loss = float(loss23)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(engine.state.params))
    # 6N per token (fwd+bwd) + attention term 12*L*hidden*seq
    flops_per_token = 6 * n_params + \
        12 * cfg.num_layers * cfg.hidden_size * seq
    return tokens_per_sec, loss, flops_per_token, profile


def main():
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    n_dev = len(jax.devices())
    tokens_per_sec, loss, flops_per_token, profile = \
        run_config(1, batch, seq, n_dev)
    gas4_tps, gas4_loss = (run_config(4, batch, seq, n_dev)[:2]
                           if batch % 4 == 0 else (None, None))

    achieved = tokens_per_sec * flops_per_token
    peak = guess_peak(jax.devices()[0]) * n_dev
    mfu = achieved / peak
    vs_baseline = mfu / 0.40

    extra = {"mfu": round(mfu, 4), "n_devices": n_dev,
             "platform": jax.devices()[0].platform,
             "device_kind": jax.devices()[0].device_kind,
             "batch": batch * n_dev, "seq": seq,
             "final_loss": loss}
    if profile is not None:
        extra["top_traffic"] = profile
    if gas4_tps is not None:
        extra["gas4_tokens_per_sec"] = round(gas4_tps, 1)
        # remaining gas4 gap is the fp32 grad accumulator's HBM traffic
        # (3 read+add+write passes over a params-sized tree per window)
        # plus micro-batch-2 matmul shapes; both shrink as micro batch
        # grows on real workloads
        extra["gas4_over_gas1"] = round(gas4_tps / tokens_per_sec, 4)
        extra["gas4_final_loss"] = gas4_loss
    print(json.dumps({
        "metric": "gpt2_small_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
