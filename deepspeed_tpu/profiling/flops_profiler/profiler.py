"""FLOPS profiler on XLA HLO cost analysis.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:23`` (1294
LoC) — it monkey-patches ``torch.nn.functional`` ops with flop-counting
wrappers and walks the module tree. On TPU the compiler already knows the
exact operation counts: ``jit(fn).lower(...).compile().cost_analysis()``
reports flops/bytes for the *optimized* HLO, so the numbers include
fusion and rematerialization — more truthful than op-by-op counting.

Public surface mirrors the reference:
  * ``get_model_profile(model, input_shape | args)`` -> (flops, macs,
    params), with ``as_string`` formatting and a per-submodule table.
  * ``FlopsProfiler(model/engine)`` with start/stop/print hooks; the
    engine consults ``flops_profiler.profile_step`` and logs the step's
    flops + achieved TFLOPS at that step.

MACs are reported as flops/2 (XLA counts one fused multiply-add as two
flops; the reference counts MACs natively).
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def _num(x, suffix=""):
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(x) < 1000.0:
            return f"{x:.2f} {unit}{suffix}"
        x /= 1000.0
    return f"{x:.2f} E{suffix}"


# bf16 dense peak per chip (the bench.py anchor table); "cpu" is a
# NOMINAL 1 TFLOP/s so MFU stays a defined, comparable number on dev
# rigs — absolute CPU MFU values are meaningless, their TRENDS are not
PEAK_FLOPS_PER_CHIP = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,
}


def peak_flops_per_device(device=None):
    """Best-effort peak model flops of one device, for MFU accounting
    (live gauge: ``ResilientTrainer``; offline: ``bench.py``).  Matches
    on ``device_kind`` substrings; unknown TPUs fall back to the v5e
    figure, non-TPU platforms to the nominal CPU figure."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS_PER_CHIP.items():
        if key in kind:
            return val
    if getattr(device, "platform", "") != "tpu":
        return PEAK_FLOPS_PER_CHIP["cpu"]
    return PEAK_FLOPS_PER_CHIP["v5e"]


def cost_analysis(fn, *args, static_argnums=(), **kwargs):
    """flops / bytes-accessed of `fn` compiled for the given args
    (concrete arrays or ShapeDtypeStructs)."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    return {
        "flops": float(costs.get("flops", 0.0)),
        "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
    }


def params_count(params):
    return sum(int(np.prod(np.shape(l))) for l in jax.tree.leaves(params))


def get_model_profile(model, input_shape=None, args=None, kwargs=None,
                      params=None, as_string=False, print_profile=True,
                      detailed=True, output_file=None, top_modules=3,
                      seed=0):
    """Profile a flax module's forward (reference ``get_model_profile``).

    input_shape: shape of an int32 token batch (causal-LM contract), or
    pass explicit `args`/`kwargs` for the module's __call__. Returns
    (flops, macs, params) — formatted strings when ``as_string``.
    """
    if args is None:
        assert input_shape is not None, "need input_shape or args"
        args = (jnp.zeros(input_shape, jnp.int32),)
    kwargs = kwargs or {}
    if params is None:
        variables = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(seed), *args, **kwargs))
        params = variables.get("params", variables)
        params = jax.tree.map(
            lambda x: x.value if hasattr(x, "value") else x, params,
            is_leaf=lambda x: hasattr(x, "value"))

    def fwd(p, *a):
        return model.apply({"params": p}, *a, **kwargs)

    shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), params)
    costs = cost_analysis(fwd, shapes, *args)
    total_flops = costs["flops"]
    total_params = params_count(params)
    macs = total_flops / 2.0

    lines = ["", "-" * 72,
             "DeepSpeed-TPU Flops Profiler (XLA HLO cost analysis)",
             "-" * 72,
             f"params:               {_num(float(total_params))}",
             f"fwd flops:            {_num(total_flops, 'FLOPs')}",
             f"fwd MACs:             {_num(macs, 'MACs')}",
             f"bytes accessed (fwd): {_num(costs['bytes_accessed'], 'B')}",
             f"flops per param:      {total_flops / max(total_params, 1):.1f}"]
    if detailed and isinstance(params, dict):
        lines += ["", "per-module parameters (depth 1):"]
        rows = sorted(((params_count(v), k) for k, v in params.items()),
                      reverse=True)
        for n, k in rows:
            pct = 100.0 * n / max(total_params, 1)
            lines.append(f"  {k:<28} {_num(float(n)):>12}  {pct:5.1f}%")
    report = "\n".join(lines)
    if print_profile:
        if output_file:
            with open(output_file, "w") as f:
                f.write(report + "\n")
        else:
            logger.info(report)
    if as_string:
        return (_num(total_flops, "FLOPs"), _num(macs, "MACs"),
                _num(float(total_params)))
    return total_flops, macs, total_params


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` — start/stop
    around a step): the engine's compiled step executables are
    cost-analyzed once; wall-clock between start/stop gives achieved
    TFLOPS."""

    def __init__(self, engine, config=None):
        self.engine = engine
        self.config = config
        self._t0 = None
        self._dt = 0.0
        self.started = False

    def start_profile(self):
        import time
        self.started = True
        self._t0 = time.time()

    def stop_profile(self):
        import time
        if self._t0 is not None:
            self._dt = time.time() - self._t0
        self.started = False

    def get_total_flops(self):
        return self.engine.flops_profile()["flops_per_step"]

    def get_total_params(self):
        return self.engine.flops_profile()["params"]

    def get_total_duration(self):
        return self._dt

    def print_profile(self, loss=None, step=None):
        self.stop_profile()
        prof = self.engine.flops_profile()
        achieved = prof["flops_per_step"] / max(self._dt, 1e-9) / 1e12
        logger.info(
            f"flops_profiler: step={step} wall={self._dt * 1e3:.1f}ms "
            f"{prof['flops_per_step'] / 1e12:.3f} TFLOPs/step "
            f"({achieved:.2f} achieved TFLOPS), "
            f"{prof['params'] / 1e6:.1f}M params")


def profile_train_step(step_fn, *example_args):
    """Cost-analyze a jitted train-step callable with example args
    (arrays or ShapeDtypeStructs); returns {'flops', 'bytes_accessed'}."""
    return cost_analysis(step_fn, *example_args)
