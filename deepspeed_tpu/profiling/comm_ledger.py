"""Static HLO communication ledger: count collective ops and bytes per
mesh axis from *compiled* executables.

The span tracer (PR 8/9) answers "where did the time go" and the memory
tier (PR 11) "where did the pages go"; this pass answers **"how many
bytes does one dispatch move over which mesh axis"** — statically, from
the post-SPMD-partitioning HLO, so the numbers include every collective
GSPMD inserted (row-parallel psums, paged-KV gather/scatter loops,
argmax all-gathers), not just the ones written in source.

How it works
------------

1. ``jit(fn).lower(args).compile().as_text()`` — the optimized,
   partitioned HLO module (the same seam ``flops_profile()``'s cost
   analysis reads).
2. Parse every computation for collective instructions (``all-reduce``,
   ``all-gather``, ``reduce-scatter``, ``all-to-all``,
   ``collective-permute``, ``collective-broadcast``, and their async
   ``-start`` halves), with operand/output byte sizes and replica
   groups (literal ``{{0,1},...}`` and iota ``[G,S]<=[dims]T(perm)``
   forms).
3. Multiply by loop trip counts: a ``lax.scan`` horizon lowers to a
   ``while`` whose body holds the collectives ONCE — the executed
   truth is body × trip.  Trip counts come from XLA's own
   ``backend_config={"known_trip_count":...}`` (with a
   condition-constant fallback); an undeterminable loop multiplies by
   1 and is counted in ``unknown_trip_counts`` rather than silently
   under-reporting.
4. Attribute each group to mesh axes: partition ids index
   ``mesh.devices`` in flat order (the device-assignment order jax
   hands XLA), so the axes a group *varies over* are exactly the mesh
   axes the traffic rides.  Tier attribution: a group whose members
   span more than one process is **DCN**-tier, else **ICI** (on a
   hybrid multi-slice mesh the outer, slice-crossing axis is the
   process boundary — the rule needs only the mesh, no hardware
   introspection).

Byte definitions (shared with ``comm/telemetry.py`` and documented in
``docs/observability.md``): ``bytes`` is the per-device payload
(operand bytes; all-gather and broadcast count the full output since
their operand is the shard), ``wire_bytes`` is the busbw numerator of
the standard ring algorithms via :func:`comm.telemetry.wire_bytes`.
All figures are per device.
"""

import re

import numpy as np

from deepspeed_tpu.comm.telemetry import wire_bytes

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: HLO collective opcodes -> canonical op name.  ``-done`` halves are
#: skipped (the ``-start`` carries the operands).
_COLLECTIVE_OPS = {
    "all-reduce": "all_reduce",
    "all-reduce-start": "all_reduce",
    "all-gather": "all_gather",
    "all-gather-start": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "collective_permute",
    "collective-permute-start": "collective_permute",
    "collective-broadcast": "broadcast",
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^(?:ROOT\s+)?%[\w.\-]+\s*=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>[\w\-]+)\(")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*\{\s*$")
_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[\d, ]*\}(?:, ?\{[\d, ]*\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[\d, ]*\}(?:, ?\{[\d, ]*\})*)\}")
_TRIP_RE = re.compile(r'known_trip_count[^0-9]*([0-9]+)')
_CALLEE_RE = {
    "body": re.compile(r"body=%?([\w.\-]+)"),
    "condition": re.compile(r"condition=%?([\w.\-]+)"),
    "to_apply": re.compile(r"to_apply=%?([\w.\-]+)"),
    "branches": re.compile(r"branch_computations=\{([^}]*)\}"),
    "true": re.compile(r"true_computation=%?([\w.\-]+)"),
    "false": re.compile(r"false_computation=%?([\w.\-]+)"),
}
_CONST_RE = re.compile(r"constant\((\d+)\)")


def _shape_bytes(s):
    """Total bytes of an HLO shape string (tuple shapes sum)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(s):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _shape_bytes_max(s):
    """Largest single component of an HLO shape string.  Async
    ``-start`` ops return ``(operand alias, result, ...)`` tuples —
    summing would double-count the shard; the RESULT (the gathered/
    reduced buffer) is the largest component."""
    best = 0
    for dt, dims in _SHAPE_RE.findall(s):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n * _DTYPE_BYTES[dt])
    return best


def _parse_brace_groups(s):
    """``{0,1}, {2,3}`` -> [[0,1],[2,3]]."""
    return [[int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([\d, ]*)\}", s)]


def _iota_groups(groups_shape, dims, perm):
    """The v2 iota replica-group format: devices are
    ``transpose(reshape(arange(prod(dims)), dims), perm)`` flattened
    then reshaped to ``groups_shape``."""
    arr = np.arange(int(np.prod(dims))).reshape(dims)
    if perm is not None:
        arr = arr.transpose(perm)
    return arr.reshape(groups_shape).tolist()


def _split_operands(line, start):
    """Return (operand_str, attr_str): scan from the '(' at ``start``
    to its matching ')'; attrs follow."""
    depth = 0
    for i in range(start, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i], line[i + 1:]
    return line[start + 1:], ""


class _Collective:
    __slots__ = ("op", "bytes_in", "bytes_out", "groups", "pairs")

    def __init__(self, op, bytes_in, bytes_out, groups, pairs):
        self.op = op
        self.bytes_in = bytes_in
        self.bytes_out = bytes_out
        self.groups = groups      # list of lists of partition ids
        self.pairs = pairs        # collective-permute (src, dst) edges


def _parse_module(text):
    """Split the HLO module into computations, each with its collective
    instructions, callee edges and while trip counts."""
    comps = {}
    entry = None
    name = None
    cur = None
    for raw in text.splitlines():
        if cur is None:
            m = _COMP_RE.match(raw)
            if m and "=" not in raw.split("(")[0]:
                name = m.group("name")
                if raw.lstrip().startswith("ENTRY"):
                    entry = name
                cur = {"collectives": [], "whiles": [], "calls": [],
                       "constants": [], "root_lt": False}
            continue
        line = raw.strip()
        if raw.startswith("}") or line == "}":
            comps[name] = cur
            cur = None
            continue
        if not line or " = " not in line:
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        op = m.group("op")
        if op == "constant" or "constant(" in line:
            cur["constants"] += [int(x) for x in _CONST_RE.findall(line)]
        if "compare(" in line and "direction=LT" in line and \
                line.startswith("ROOT"):
            cur["root_lt"] = True
        if op == "while":
            body = _CALLEE_RE["body"].search(line)
            cond = _CALLEE_RE["condition"].search(line)
            trip = _TRIP_RE.search(line)
            cur["whiles"].append(
                (body.group(1) if body else None,
                 cond.group(1) if cond else None,
                 int(trip.group(1)) if trip else None))
            continue
        if op in ("call", "conditional"):
            if op == "conditional":
                cur["conditionals"] = cur.get("conditionals", 0) + 1
            for key in ("to_apply", "true", "false"):
                cm = _CALLEE_RE[key].search(line)
                if cm:
                    cur["calls"].append(cm.group(1))
            bm = _CALLEE_RE["branches"].search(line)
            if bm:
                cur["calls"] += [b.strip().lstrip("%")
                                 for b in bm.group(1).split(",") if b.strip()]
            continue
        if op not in _COLLECTIVE_OPS:
            continue
        paren = line.find("(", m.start("op"))
        operands, attrs = _split_operands(line, paren)
        groups = None
        gm = _GROUPS_LITERAL_RE.search(attrs)
        if gm:
            groups = _parse_brace_groups(gm.group(1))
        else:
            im = _GROUPS_IOTA_RE.search(attrs)
            if im:
                gshape = [int(x) for x in im.group(1).split(",")]
                dims = [int(x) for x in im.group(2).split(",")]
                perm = [int(x) for x in im.group(3).split(",")] \
                    if im.group(3) else None
                groups = _iota_groups(gshape, dims, perm)
        pairs = None
        pm = _PAIRS_RE.search(attrs)
        if pm:
            pairs = [tuple(p) for p in _parse_brace_groups(pm.group(1))]
        out_bytes = _shape_bytes_max(m.group("shape")) \
            if op.endswith("-start") else _shape_bytes(m.group("shape"))
        cur["collectives"].append(_Collective(
            _COLLECTIVE_OPS[op], _shape_bytes(operands), out_bytes,
            groups, pairs))
    return comps, entry


def _trip_count(comps, body, cond, explicit):
    """Trip count of one while: XLA's known_trip_count when present,
    else the single integer constant of a canonical ``i < N``
    condition; None when undeterminable."""
    if explicit is not None:
        return explicit
    c = comps.get(cond)
    if c and c["root_lt"]:
        consts = sorted(set(c["constants"]))
        if len(consts) == 1:
            return consts[0]
    return None


def _multipliers(comps, entry):
    """Executed-times multiplier per computation from the call graph
    (HLO computations cannot recurse, so contribution propagation
    terminates).  Returns (multiplier map, unknown-trip count)."""
    mult = {c: 0 for c in comps}
    unknown = 0
    stack = [(entry, 1)]
    while stack:
        name, m = stack.pop()
        if name not in comps or m == 0:
            continue
        mult[name] += m
        comp = comps[name]
        for body, cond, explicit in comp["whiles"]:
            trip = _trip_count(comps, body, cond, explicit)
            if trip is None:
                unknown += 1
                trip = 1
            if body:
                stack.append((body, m * trip))
            if cond:
                stack.append((cond, m * trip))
        for callee in comp["calls"]:
            stack.append((callee, m))
    return mult, unknown


def _group_axes(groups, mesh_sizes, mesh_names):
    """Mesh axes the group traffic varies over -> a '+'-joined label
    ('' for trivial groups)."""
    varying = set()
    for g in groups:
        if len(g) < 2:
            continue
        base = np.unravel_index(int(g[0]), mesh_sizes)
        for pid in g[1:]:
            c = np.unravel_index(int(pid), mesh_sizes)
            for ax, a, b in zip(mesh_names, base, c):
                if a != b:
                    varying.add(ax)
    return "+".join(ax for ax in mesh_names if ax in varying)


def _group_tier(groups, procs):
    """'dcn' when any group spans more than one OS process, else
    'ici' — the hybrid-mesh tier attribution rule."""
    for g in groups:
        if len({procs[int(p)] for p in g if int(p) < len(procs)}) > 1:
            return "dcn"
    return "ici"


def ledger_from_hlo(text, mesh=None):
    """Build the communication ledger of one compiled HLO module.

    Returns a plain dict (JSON-ready): trip-weighted per-device totals
    (``collectives``, ``bytes``, ``wire_bytes``), the per-op split
    (``per_op``), per-mesh-axis wire bytes (``per_axis`` — multi-axis
    groups key as ``'data+model'``), the per-(axis, op) breakdown
    (``per_axis_op``), ICI/DCN tier wire bytes (``per_tier``), the
    static instruction count and ``unknown_trip_counts``."""
    comps, entry = _parse_module(text)
    mult, unknown = _multipliers(comps, entry) if entry is not None \
        else ({c: 1 for c in comps}, 0)
    # conditionals: every branch is counted as if executed (an upper
    # bound — exactly one branch runs per dispatch), so the overcount
    # is FLAGGED rather than silent, like unknown_trip_counts
    conditionals = sum(c.get("conditionals", 0) * mult.get(n, 0)
                       for n, c in comps.items())
    if mesh is not None:
        mesh_sizes = tuple(int(s) for s in mesh.devices.shape)
        mesh_names = tuple(str(a) for a in mesh.axis_names)
        procs = [getattr(d, "process_index", 0)
                 for d in np.asarray(mesh.devices).flat]
    else:
        mesh_sizes = mesh_names = procs = None
    out = {"instructions": 0, "collectives": 0, "bytes": 0,
           "wire_bytes": 0, "per_op": {}, "per_axis": {},
           "per_axis_op": {}, "per_tier": {"ici": 0, "dcn": 0},
           "unknown_trip_counts": unknown,
           "conditional_branches": int(conditionals)}
    for name, comp in comps.items():
        m = mult.get(name, 0)
        for c in comp["collectives"]:
            if m == 0:
                continue
            out["instructions"] += 1
            groups = c.groups
            if groups is None and c.pairs is not None:
                # permute edges: groups of the communicating pairs
                groups = [[s, d] for s, d in c.pairs if s != d]
            if not groups:
                continue
            n = max(len(g) for g in groups) if c.pairs is None else 2
            if c.pairs is not None:
                # per sending device: payload leaves only on non-self
                # edges; average over the participating senders
                nonself = sum(1 for s, d in c.pairs if s != d)
                frac = nonself / max(len(c.pairs), 1)
                payload = int(c.bytes_in * frac)
                wire = payload
            else:
                payload = c.bytes_out \
                    if c.op in ("all_gather", "broadcast") else c.bytes_in
                wire = wire_bytes(c.op, c.bytes_in, c.bytes_out, n)
            axis = "" if mesh_names is None else \
                _group_axes(groups, mesh_sizes, mesh_names)
            axis = axis or "replicated"
            tier = "ici" if procs is None else _group_tier(groups, procs)
            out["collectives"] += m
            out["bytes"] += m * payload
            out["wire_bytes"] += m * wire
            po = out["per_op"].setdefault(
                c.op, {"count": 0, "bytes": 0, "wire_bytes": 0})
            po["count"] += m
            po["bytes"] += m * payload
            po["wire_bytes"] += m * wire
            out["per_axis"][axis] = out["per_axis"].get(axis, 0) + m * wire
            pao = out["per_axis_op"].setdefault(axis, {})
            pa = pao.setdefault(c.op, {"count": 0, "bytes": 0,
                                       "wire_bytes": 0})
            pa["count"] += m
            pa["bytes"] += m * payload
            pa["wire_bytes"] += m * wire
            out["per_tier"][tier] += m * wire
    return out


def ledger_for(fn, *args, mesh=None, static_argnums=(), **kwargs):
    """Ledger of ``fn`` compiled for the given args (concrete arrays or
    ShapeDtypeStructs carrying shardings) — the comm twin of
    ``profiling.flops_profiler.cost_analysis``, reading the same
    lower->compile seam."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args, **kwargs).compile()
    return ledger_from_hlo(compiled.as_text(), mesh=mesh)


def merge_ledgers(ledgers):
    """Sum ledgers (e.g. the gas>1 micro/boundary executables of one
    optimizer step, each pre-scaled with :func:`scale_ledger`)."""
    out = None
    for led in ledgers:
        if out is None:
            out = scale_ledger(led, 1)
            continue
        for k in ("instructions", "collectives", "bytes", "wire_bytes",
                  "unknown_trip_counts", "conditional_branches"):
            out[k] += led.get(k, 0)
        for op, v in led["per_op"].items():
            po = out["per_op"].setdefault(
                op, {"count": 0, "bytes": 0, "wire_bytes": 0})
            for k in po:
                po[k] += v[k]
        for ax, v in led["per_axis"].items():
            out["per_axis"][ax] = out["per_axis"].get(ax, 0) + v
        for ax, ops in led["per_axis_op"].items():
            pao = out["per_axis_op"].setdefault(ax, {})
            for op, v in ops.items():
                pa = pao.setdefault(op, {"count": 0, "bytes": 0,
                                         "wire_bytes": 0})
                for k in pa:
                    pa[k] += v[k]
        for t in ("ici", "dcn"):
            out["per_tier"][t] += led["per_tier"][t]
    return out


def scale_ledger(ledger, k):
    """``ledger`` with every count/byte figure multiplied by ``k``
    (gradient-accumulation micro repeats)."""
    out = {"instructions": ledger["instructions"] * k,
           "collectives": ledger["collectives"] * k,
           "bytes": ledger["bytes"] * k,
           "wire_bytes": ledger["wire_bytes"] * k,
           "per_op": {op: {kk: vv * k for kk, vv in v.items()}
                      for op, v in ledger["per_op"].items()},
           "per_axis": {ax: v * k for ax, v in ledger["per_axis"].items()},
           "per_axis_op": {ax: {op: {kk: vv * k for kk, vv in v.items()}
                                for op, v in ops.items()}
                           for ax, ops in ledger["per_axis_op"].items()},
           "per_tier": {t: v * k for t, v in ledger["per_tier"].items()},
           "unknown_trip_counts": ledger["unknown_trip_counts"] * k,
           "conditional_branches":
               ledger.get("conditional_branches", 0) * k}
    return out
