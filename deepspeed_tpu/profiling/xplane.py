"""Minimal XSpace (xplane.pb) reader — no tensorflow/tensorboard needed.

jax.profiler.trace writes TPU op-level timing as an XSpace protobuf
(tsl/profiler/protobuf/xplane.proto). The tensorboard profile plugin
that normally reads it drags in tensorflow + a protobuf-version
minefield, so this module hand-decodes the handful of fields the
per-module profiler consumes (field numbers verified against
tsl xplane_pb2):

    XSpace.planes = 1
    XPlane.name = 2, .lines = 3, .event_metadata = 4 (map),
          .stat_metadata = 5 (map)
    XLine.name = 2, .events = 4
    XEvent.metadata_id = 1, .duration_ps = 3, .stats = 4
    XEventMetadata.id = 1, .name = 2, .stats = 5
    XStat.metadata_id = 1, double=2, uint64=3, int64=4, str=5, bytes=6,
          ref=7
    XStatMetadata.id = 1, .name = 2

Wire format is standard protobuf: this is a ~100-line varint/length-
delimited walker, not a general proto library.
"""

import dataclasses
from typing import Any, Dict, List


def _varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer.
    value: int for varint/fixed, memoryview for length-delimited."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        elif wt == 1:
            v = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


@dataclasses.dataclass
class Event:
    metadata_id: int
    duration_ps: int
    stats: Dict[str, Any]


@dataclasses.dataclass
class Line:
    name: str
    events: List[Event]


@dataclasses.dataclass
class Plane:
    name: str
    lines: List[Line]
    event_names: Dict[int, str]   # metadata_id -> op name
    event_stats: Dict[int, Dict[str, Any]]   # metadata-level stats


def _stat(buf, stat_names):
    mid = 0
    val = None
    for fno, wt, v in _fields(buf):
        if fno == 1:
            mid = v
        elif fno == 2 and wt == 1:   # double
            import struct
            val = struct.unpack("<d", v.to_bytes(8, "little"))[0]
        elif fno in (3, 4, 7):
            val = v
        elif fno == 5:
            val = bytes(v).decode("utf-8", "replace")
        elif fno == 6:
            val = bytes(v)
    return stat_names.get(mid, f"stat{mid}"), val


def _event(buf, stat_names):
    mid = dur = 0
    stats = {}
    for fno, wt, v in _fields(buf):
        if fno == 1:
            mid = v
        elif fno == 3:
            dur = v
        elif fno == 4:
            k, sv = _stat(bytes(v), stat_names)
            stats[k] = sv
    return Event(mid, dur, stats)


def _map_entry(buf):
    """proto map<k, v> entry: key=1, value=2 (message)."""
    key = None
    val = None
    for fno, wt, v in _fields(buf):
        if fno == 1:
            key = v
        elif fno == 2:
            val = bytes(v)
    return key, val


def _named_id(buf):
    """(id=1, name=2) prefix shared by XEventMetadata/XStatMetadata;
    also returns raw submessages of field 5 (metadata-level stats)."""
    mid = 0
    name = ""
    stat_bufs = []
    for fno, wt, v in _fields(buf):
        if fno == 1:
            mid = v
        elif fno == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 5 and wt == 2:
            stat_bufs.append(bytes(v))
    return mid, name, stat_bufs


def _plane(buf):
    name = ""
    line_bufs = []
    em_bufs = []
    sm_bufs = []
    for fno, wt, v in _fields(buf):
        if fno == 2:
            name = bytes(v).decode("utf-8", "replace")
        elif fno == 3:
            line_bufs.append(bytes(v))
        elif fno == 4:
            em_bufs.append(bytes(v))
        elif fno == 5:
            sm_bufs.append(bytes(v))

    stat_names = {}
    for b in sm_bufs:
        _, vb = _map_entry(b)
        if vb is not None:
            mid, sname, _ = _named_id(vb)
            stat_names[mid] = sname

    event_names = {}
    event_stats = {}
    for b in em_bufs:
        _, vb = _map_entry(b)
        if vb is not None:
            mid, ename, stat_bufs = _named_id(vb)
            event_names[mid] = ename
            if stat_bufs:
                event_stats[mid] = dict(
                    _stat(sb, stat_names) for sb in stat_bufs)

    lines = []
    for lb in line_bufs:
        lname = ""
        ev_bufs = []
        for fno, wt, v in _fields(lb):
            if fno == 2:
                lname = bytes(v).decode("utf-8", "replace")
            elif fno == 4:
                ev_bufs.append(bytes(v))
        lines.append(Line(lname, [_event(eb, stat_names)
                                  for eb in ev_bufs]))
    return Plane(name, lines, event_names, event_stats)


def read_xspace(path):
    """Parse an .xplane.pb file -> list of Plane."""
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for fno, wt, v in _fields(buf):
        if fno == 1:
            planes.append(_plane(bytes(v)))
    return planes


def device_plane(planes):
    """The TPU (or first device) plane with op events."""
    for p in planes:
        if p.name.startswith("/device:TPU") and any(
                l.name == "XLA Ops" for l in p.lines):
            return p
    for p in planes:
        if any(l.name == "XLA Ops" for l in p.lines):
            return p
    return None
