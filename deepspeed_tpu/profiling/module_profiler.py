"""Per-module flops / bytes / latency breakdown from a real device trace.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:23`` prints
per-module flops/MACs/latency by monkey-patching torch.nn.functional.
On TPU the ground truth is better: ``jax.profiler.trace`` records every
XLA op's measured device time, its flop count and HBM bytes accessed,
AND the originating module path (flax named_scopes flow into the HLO
metadata as the ``tf_op`` stat, e.g.
``jit(step)/GPT2/h_3/attn/qkv/dot_general``). This module captures one
traced step and aggregates those records into the reference-style
module tree — with measured (post-fusion) numbers rather than analytic
estimates, so it finds layout copies and bandwidth sinks the analytic
profiler cannot see.
"""

import glob
import os
import re
import shutil
import tempfile
from collections import defaultdict

import jax

from deepspeed_tpu.profiling.xplane import device_plane, read_xspace
from deepspeed_tpu.utils.logging import logger

_JIT_PREFIX = re.compile(r"^jit\([^)]*\)/")


def capture_trace(step_fn, n_steps=3, trace_dir=None):
    """Run ``step_fn`` (already warmed/compiled) ``n_steps`` times under
    the jax profiler; returns the op records from the device plane.

    Record: {"op", "module", "leaf_op", "category", "duration_ps",
    "flops", "bytes", "occurrences"} aggregated over the traced steps.
    """
    own = trace_dir is None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="ds_modprof_")
    try:
        with jax.profiler.trace(trace_dir):
            out = None
            for _ in range(n_steps):
                out = step_fn()
            # fence through a host transfer: block_until_ready can
            # return early through relayed device transports
            leaf = jax.tree.leaves(out)[0] if out is not None else None
            if leaf is not None and hasattr(leaf, "dtype"):
                import jax.numpy as jnp
                float(jax.device_get(jnp.sum(leaf.astype(jnp.float32))))
        files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                          recursive=True)
        if not files:
            raise RuntimeError(
                "jax.profiler.trace produced no xplane file — the "
                "backend may not support device tracing")
        plane = device_plane(read_xspace(sorted(files)[-1]))
        if plane is None:
            raise RuntimeError("no device plane with XLA Ops in trace")
        return _aggregate(plane, n_steps)
    finally:
        if own:
            shutil.rmtree(trace_dir, ignore_errors=True)


def _aggregate(plane, n_steps):
    by_op = {}
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            meta_stats = plane.event_stats.get(ev.metadata_id, {})
            stats = {**meta_stats, **ev.stats}
            name = plane.event_names.get(ev.metadata_id, "?")
            rec = by_op.setdefault(ev.metadata_id, {
                "op": name.split(" = ")[0].lstrip("%"),
                "module": _module_path(stats.get("tf_op", "")),
                "leaf_op": _leaf_op(stats.get("tf_op", "")),
                "category": stats.get("hlo_category", ""),
                "duration_ps": 0, "flops": 0, "bytes": 0,
                "occurrences": 0,
            })
            rec["duration_ps"] += ev.duration_ps
            rec["occurrences"] += 1
            rec["flops"] += int(stats.get("flops") or 0)
            rec["bytes"] += int(stats.get("raw_bytes_accessed")
                                or stats.get("bytes_accessed") or 0)
    recs = list(by_op.values())
    for r in recs:
        r["steps"] = n_steps
    return recs


def _module_path(tf_op):
    """'jit(f)/transpose(jvp(GPT2))/h_0/attn/qkv/dot_general:' ->
    'GPT2/h_0/attn/qkv [bwd]' — the jvp/transpose autodiff wrappers
    become a fwd/bwd phase tag instead of polluting the tree."""
    if not tf_op:
        return "(unattributed)"
    p = _JIT_PREFIX.sub("", tf_op).rstrip(":")
    parts = p.split("/")
    head, phase = parts[0], ""
    if head.startswith("transpose("):
        phase = " [bwd]"
        head = head[len("transpose("):].rstrip(")")
    if head.startswith("jvp("):
        if not phase:
            phase = " [fwd]"
        head = head[len("jvp("):].rstrip(")")
    parts[0] = head
    mod = "/".join(p2 for p2 in parts[:-1] if p2)
    return (mod or "(top)") + phase


def _leaf_op(tf_op):
    if not tf_op:
        return ""
    return _JIT_PREFIX.sub("", tf_op).rstrip(":").split("/")[-1]


def aggregate_by_module(records, depth=3):
    """Group op records by module-path prefix of ``depth`` components.
    Returns rows sorted by time desc:
    (module, ms_per_step, flops_per_step, gb_per_step, share)."""
    groups = defaultdict(lambda: [0, 0, 0])
    total_ps = 0
    for r in records:
        key = "/".join(r["module"].split("/")[:depth])
        g = groups[key]
        g[0] += r["duration_ps"]
        g[1] += r["flops"]
        g[2] += r["bytes"]
        total_ps += r["duration_ps"]
    n = records[0]["steps"] if records else 1
    rows = []
    for mod, (ps, fl, by) in groups.items():
        rows.append({
            "module": mod,
            "ms": ps / 1e9 / n,
            "gflops": fl / 1e9 / n,
            "gb": by / 1e9 / n,
            "share": ps / total_ps if total_ps else 0.0,
        })
    rows.sort(key=lambda r: -r["ms"])
    return rows


def top_traffic_consumers(records, k=3):
    """The k op groups moving the most HBM bytes per step — the tool
    that finds layout transposes and unfused read passes (VERDICT r4
    task 7's acceptance probe)."""
    groups = defaultdict(lambda: [0, 0])
    for r in records:
        key = (r["module"], r["leaf_op"] or r["category"])
        groups[key][0] += r["bytes"]
        groups[key][1] += r["duration_ps"]
    n = records[0]["steps"] if records else 1
    rows = [{"module": m, "op": o, "gb": b / 1e9 / n,
             "ms": ps / 1e9 / n}
            for (m, o), (b, ps) in groups.items()]
    rows.sort(key=lambda r: -r["gb"])
    return rows[:k]


def format_profile(records, depth=3, top=25):
    """Reference print_model_profile-style table."""
    rows = aggregate_by_module(records, depth)
    n = records[0]["steps"] if records else 1
    tot_ms = sum(r["ms"] for r in rows)
    tot_gf = sum(r["gflops"] for r in rows)
    tot_gb = sum(r["gb"] for r in rows)
    out = [f"per-module profile (measured device trace, {n} steps)",
           f"{'module':44s} {'ms/step':>9s} {'GFLOP':>9s} "
           f"{'GB':>7s} {'share':>6s}"]
    for r in rows[:top]:
        out.append(f"{r['module'][:44]:44s} {r['ms']:9.3f} "
                   f"{r['gflops']:9.2f} {r['gb']:7.3f} "
                   f"{r['share']:6.1%}")
    out.append(f"{'TOTAL':44s} {tot_ms:9.3f} {tot_gf:9.2f} "
               f"{tot_gb:7.3f} {1:6.1%}")
    out.append("top HBM traffic consumers:")
    for t in top_traffic_consumers(records):
        out.append(f"  {t['gb']:7.3f} GB/step  {t['ms']:7.3f} ms  "
                   f"{t['module']}/{t['op']}")
    return "\n".join(out)
