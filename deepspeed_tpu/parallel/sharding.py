"""Sharding rules: logical param axes -> mesh axes, plus ZeRO staging.

This module is the TPU replacement for the reference's partition bookkeeping
(``runtime/zero/stage_1_and_2.py``, ``stage3.py``, ``partition_parameters.py``):
instead of slicing flat buffers and tracking ownership, each array in the
train state gets a ``NamedSharding`` and XLA materializes the all-gathers /
reduce-scatters (reference `stage_1_and_2.py:894`, `stage3.py:1076`) as
collectives over ICI.

Models annotate params with *logical* axis names (flax
``nn.with_partitioning``). ``logical_to_mesh_axes`` maps them through
t5x-style rules; ZeRO stages then add `data`-axis sharding:

  stage 1 — optimizer state sharded over `data`
  stage 2 — + gradient accumulator sharded over `data`
  stage 3 — + parameters sharded over `data` (fsdp)
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default logical-axis rules (logical name -> mesh axis). First match wins;
# an axis already taken by another dim of the same param is skipped.
DEFAULT_LOGICAL_AXIS_RULES = (
    ("batch", "data"),
    ("pipe", "pipe"),
    ("vocab", "model"),
    ("embed", None),
    ("heads", "model"),
    ("kv", None),
    ("mlp", "model"),
    ("expert", "expert"),
    ("expert_mlp", "model"),
    ("seq", "sequence"),
    ("layers", None),
    ("stack", None),
    ("norm", None),
)


def logical_to_mesh_axes(logical_spec, rules=DEFAULT_LOGICAL_AXIS_RULES):
    """Map a tuple of logical axis names to mesh axis names (or None)."""
    if logical_spec is None:
        return None
    rules_d = dict(rules)
    out = []
    used = set()
    for name in logical_spec:
        ax = rules_d.get(name) if name is not None else None
        if ax is not None and ax in used:
            ax = None
        if ax is not None:
            used.add(ax)
        out.append(ax)
    return tuple(out)


def _axis_size(mesh, axis):
    return mesh.shape[axis] if axis in mesh.shape else 1


def add_fsdp_axis(spec, shape, mesh, fsdp_axis="data"):
    """Add `fsdp_axis` to the largest divisible, not-yet-sharded dim of spec.

    This is the ZeRO partitioning decision: the reference flattens and
    slices 1/world per rank (`partition_parameters.py:224`); here we shard a
    whole dimension so the array stays a clean XLA tile.
    """
    size = _axis_size(mesh, fsdp_axis)
    if size == 1 or not shape:
        return spec
    spec = list(spec) if spec is not None else [None] * len(shape)
    spec += [None] * (len(shape) - len(spec))
    used = {a for s in spec if s is not None for a in ((s,) if isinstance(s, str) else s)}
    if fsdp_axis in used:
        return tuple(spec)
    # pick the largest dim divisible by the axis size that is unsharded
    best, best_dim = -1, -1
    for i, (d, s) in enumerate(zip(shape, spec)):
        if s is None and d % size == 0 and d > best:
            best, best_dim = d, i
    if best_dim < 0:
        return tuple(spec)  # nothing divisible: leave replicated
    spec[best_dim] = fsdp_axis
    return tuple(spec)


def _base_pspec(logical_spec, shape, mesh, zero_stage, min_fsdp_stage, rules,
                fsdp_axis):
    """TP spec from logical names + `data`-axis sharding once the ZeRO stage
    reaches the threshold (params at stage 3, optimizer state at stage 1)."""
    mesh_axes = logical_to_mesh_axes(logical_spec, rules)
    if mesh_axes is None:
        mesh_axes = (None,) * len(shape)
    # drop axes that don't divide the dim (tiny fixtures / odd vocab)
    mesh_axes = tuple(
        a if (a is None or (dim % _axis_size(mesh, a) == 0 and _axis_size(mesh, a) > 1)) else None
        for a, dim in zip(mesh_axes, shape))
    if zero_stage >= min_fsdp_stage:
        mesh_axes = add_fsdp_axis(mesh_axes, shape, mesh, fsdp_axis)
    return P(*mesh_axes)


def param_pspec(logical_spec, shape, mesh, zero_stage=0, rules=DEFAULT_LOGICAL_AXIS_RULES,
                fsdp_axis="data", persist_threshold=0):
    """PartitionSpec for a parameter under TP rules + ZeRO stage.

    ``persist_threshold`` is the reference's
    ``stage3_param_persistence_threshold`` (zero/config.py): parameters
    with fewer elements stay replicated over the fsdp axis (their
    all-gather would cost more latency than the memory saved). TP axes
    still apply — persistence is a ZeRO decision only."""
    if persist_threshold and int(np.prod(shape or (1,))) < persist_threshold:
        zero_stage = min(zero_stage, 2)
    return _base_pspec(logical_spec, shape, mesh, zero_stage, 3, rules, fsdp_axis)


def optstate_pspec(logical_spec, shape, mesh, zero_stage=0,
                   rules=DEFAULT_LOGICAL_AXIS_RULES, fsdp_axis="data"):
    """PartitionSpec for optimizer state mirroring a parameter."""
    return _base_pspec(logical_spec, shape, mesh, zero_stage, 1, rules, fsdp_axis)


def get_logical_specs(variables):
    """Extract logical PartitionSpecs from a flax params tree with
    nn.Partitioned metadata; plain arrays get None."""
    import flax.linen as nn

    def f(x):
        if isinstance(x, nn.Partitioned):
            return x.names
        return None

    return jax.tree.map(f, variables,
                        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def unbox(variables):
    """Strip flax Partitioned boxes -> raw arrays."""
    import flax.linen as nn
    return jax.tree.map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x, variables,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def tree_param_shardings(mesh, shapes, logical_specs, zero_stage=0,
                         rules=DEFAULT_LOGICAL_AXIS_RULES):
    """NamedSharding tree for params."""
    return jax.tree.map(
        lambda sh, sp: NamedSharding(
            mesh, param_pspec(sp, sh.shape, mesh, zero_stage, rules)),
        shapes, logical_specs,
        is_leaf=lambda x: x is None or isinstance(x, tuple))


def tree_pspecs(mesh, shapes, logical_specs, zero_stage, kind,
                rules=DEFAULT_LOGICAL_AXIS_RULES, persist_threshold=0):
    """PartitionSpec tree for params ('param') or optimizer state ('opt').
    ``persist_threshold`` applies to params only (see param_pspec)."""
    if kind == "param":
        def leaf(sh, sp):
            return param_pspec(sp, sh.shape, mesh, zero_stage, rules,
                               persist_threshold=persist_threshold)
    else:
        def leaf(sh, sp):
            return optstate_pspec(sp, sh.shape, mesh, zero_stage, rules)

    return jax.tree.map(leaf, shapes, logical_specs,
                        is_leaf=lambda x: x is None or isinstance(x, tuple))


def opt_state_pspecs(opt_state_shapes, params_shapes, params_pspecs):
    """PartitionSpec tree for an optax opt_state.

    Optimizer moments (adam mu/nu, momentum trace, ...) are sub-trees with
    the *same tree structure* as the param tree, so they are detected
    structurally and get the param specs position-for-position — robust even
    when two same-shaped params carry different specs. Remaining leaves
    (step counters, scalars) are replicated.
    """
    pdef = jax.tree.structure(params_shapes)
    pshapes = [tuple(s.shape) for s in jax.tree.leaves(params_shapes)]
    pspecs_flat = jax.tree.leaves(params_pspecs, is_leaf=lambda x: isinstance(x, P))
    specs_tree = jax.tree.unflatten(pdef, pspecs_flat)

    def is_params_like(x):
        try:
            if jax.tree.structure(x) != pdef:
                return False
            return [tuple(l.shape) for l in jax.tree.leaves(x)] == pshapes
        except Exception:
            return False

    def f(node):
        if is_params_like(node):
            return specs_tree
        return P()

    return jax.tree.map(f, opt_state_shapes, is_leaf=is_params_like)


def apply_shardings(tree, mesh, pspecs):
    """device_put a pytree with NamedShardings from a PartitionSpec tree."""
    flat, treedef = jax.tree.flatten(tree)
    flat_specs = treedef.flatten_up_to(pspecs)
    out = [jax.device_put(x, NamedSharding(mesh, p)) for x, p in zip(flat, flat_specs)]
    return jax.tree.unflatten(treedef, out)


def tree_shardings(mesh, pspecs):
    """NamedSharding tree from a PartitionSpec tree."""
    return jax.tree.map(lambda p: NamedSharding(mesh, p), pspecs,
                        is_leaf=lambda x: isinstance(x, P))
