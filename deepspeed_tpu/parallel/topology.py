"""Process/device topology math and the global device mesh.

Reimplements the pure-math core of the reference's
``deepspeed/runtime/pipe/topology.py`` (``ProcessTopology`` :12,
``PipeModelDataParallelTopology`` :244) and replaces its process-group
plumbing with a single ``jax.sharding.Mesh`` carrying named axes
``(pipe, data, expert, sequence, model)``.

Axis order is chosen for ICI locality: ``model`` (tensor parallel) is the
innermost/fastest-varying axis so TP collectives ride neighboring chips;
``pipe`` is outermost so stage boundaries can span DCN.
"""

from collections import namedtuple
from itertools import product as cartesian_product

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical axis order, outermost -> innermost.
MESH_AXES = ("pipe", "data", "expert", "sequence", "model")


class ProcessTopology:
    """Cartesian product of parallelism axes -> rank mapping (pure math).

    Mirrors reference ``runtime/pipe/topology.py:12`` behavior: axes is a list
    of axis names ordered outermost-first, dims the matching sizes. The rank
    of a coordinate is its row-major index.
    """

    def __init__(self, axes, dims):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(cartesian_product(*ranges)):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that vary along `axis` with all others fixed."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in cartesian_product(*ranges):
            other = dict(zip(other_axes, coord))
            sub = [self.get_rank(**{axis: i}, **other) for i in range(self.get_dim(axis))]
            lists.append(sub)
        return lists

    def filter_match(self, **filter_kwargs):
        def _filter_helper(x):
            for key, val in filter_kwargs.items():
                if getattr(x, key) != val:
                    return False
            return True

        coords = filter(_filter_helper, self.mapping.keys())
        return [self.mapping[coord] for coord in coords]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return int(np.prod(self.dims)) if self.dims else 1

    def __str__(self):
        return str(self.mapping)


class PipeModelDataParallelTopology(ProcessTopology):
    """3D (pipe, data, model) topology (reference :244)."""

    def __init__(self, num_pp, num_dp, num_mp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipeDataParallelTopology(ProcessTopology):
    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


def resolve_mesh_dims(mesh_config, n_devices, allow_subset=False):
    """Resolve -1 on at most one axis to 'all remaining devices'.

    `allow_subset=True` (inference) permits a mesh smaller than the host's
    device count; training keeps the strict all-devices check so a
    mis-sized config fails loudly instead of silently idling chips."""
    sizes = {ax: getattr(mesh_config, ax, 1) or 1 for ax in MESH_AXES}
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes product {fixed}")
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total > n_devices or (total != n_devices and not allow_subset):
        raise ValueError(
            f"mesh {sizes} needs {total} devices but {n_devices} are available")
    return sizes


def make_mesh(mesh_config=None, devices=None, allow_subset=False):
    """Build the global Mesh from a MeshConfig (or use all devices on `data`)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_config is None:
        sizes = {ax: 1 for ax in MESH_AXES}
        sizes["data"] = n
    else:
        sizes = resolve_mesh_dims(mesh_config, n, allow_subset=allow_subset)
    shape = tuple(sizes[ax] for ax in MESH_AXES)
    total = int(np.prod(shape))
    devices = list(devices)[:total]
    try:
        from jax.experimental import mesh_utils
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


def make_hybrid_mesh(mesh_config=None, dcn_sizes=None, devices=None,
                     allow_subset=False):
    """Multi-slice ICI x DCN mesh: per-axis size = ici * dcn (the
    t5x/MaxText hybrid split).  ``mesh_config`` carries the ICI
    (within-slice) sizes — ``-1`` resolves against the PER-SLICE device
    count — and ``dcn_sizes`` maps axis names to their across-slice
    (DCN) factors.  On real multi-slice TPU pods the device array comes
    from ``mesh_utils.create_hybrid_device_mesh`` (devices grouped by
    ``slice_index``, DCN-major per axis so ICI neighbors stay
    physically adjacent); single-slice/CPU runtimes — where devices
    carry no slice attribution — fall back to the same DCN-major
    per-axis layout over the flat device list, so the topology is pure
    config everywhere and CI exercises the exact axis arithmetic a pod
    run uses.

    Keep ``model`` (tensor parallel) ICI-only: a ``dcn_sizes['model']``
    factor is legal config but puts per-layer collectives on the slow
    across-slice links — the serving rule table maps ``slots`` to the
    DCN-spanning ``data`` axis precisely so per-token traffic never
    crosses DCN."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    dcn = {ax: int((dcn_sizes or {}).get(ax, 1) or 1) for ax in MESH_AXES}
    bad = [f"{ax}={s}" for ax, s in dcn.items() if s < 1]
    if bad:
        raise ValueError(f"dcn mesh sizes must be >= 1 (no -1 wildcard "
                         f"across slices): {', '.join(bad)}")
    unknown = set(dcn_sizes or {}) - set(MESH_AXES)
    if unknown:
        raise ValueError(f"unknown dcn mesh axes {sorted(unknown)}; "
                         f"valid axes: {MESH_AXES}")
    total_dcn = int(np.prod(list(dcn.values())))
    if n % total_dcn != 0:
        raise ValueError(
            f"dcn mesh {dcn_sizes} needs a device count divisible by "
            f"{total_dcn}, got {n}")
    ici = resolve_mesh_dims(mesh_config, n // total_dcn,
                            allow_subset=allow_subset) \
        if mesh_config is not None else \
        {ax: (n // total_dcn if ax == "data" else 1) for ax in MESH_AXES}
    ici_shape = tuple(ici[ax] for ax in MESH_AXES)
    dcn_shape = tuple(dcn[ax] for ax in MESH_AXES)
    total = int(np.prod(ici_shape)) * total_dcn
    devices = list(devices)[:total]
    if getattr(devices[0], "slice_index", None) is not None:
        # real multi-slice pod: slice membership is ground truth, and
        # any shape/topology mismatch must fail LOUDLY here — falling
        # back to a flat-list layout would silently put "ICI" neighbors
        # across DCN and tank every per-layer collective
        from jax.experimental import mesh_utils
        device_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    else:
        # single-slice / CPU devices carry no slice attribution:
        # emulate the hybrid layout — DCN-major per axis, matching
        # create_hybrid_device_mesh's semantics (slice-local blocks
        # stay contiguous on every combined axis) — so CI exercises
        # the exact axis arithmetic a pod run uses
        arr = np.asarray(devices).reshape(dcn_shape + ici_shape)
        nd = len(MESH_AXES)
        perm = []
        for i in range(nd):
            perm += [i, nd + i]
        device_array = arr.transpose(perm).reshape(
            tuple(d * i for d, i in zip(dcn_shape, ici_shape)))
    return Mesh(device_array, MESH_AXES)


def single_device_mesh(device=None):
    device = device or jax.devices()[0]
    arr = np.asarray([device]).reshape((1,) * len(MESH_AXES))
    return Mesh(arr, MESH_AXES)
