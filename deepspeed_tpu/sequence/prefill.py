"""Sequence-parallel PREFILL attention: transport dispatch.

One entry point for the serving models' paged prefill branch: given the
fresh chunk q/k/v (sequence-sharded), the paged-pool gather of the
prefix (sequence-replicated), and the resolved transport, run the chunk
attention distributed over the ``sequence`` mesh axis.

Transport selection lives in ``serving.sharding.resolve_sequence_plan``
(the scheduler/engine resolve it ONCE, at serving setup); this module
only dispatches on the already-chosen ``impl`` string, which reaches
the jitted model code as a static trace-time cache value:

* ``"ulysses"`` — all-to-all head-scatter/seq-gather
  (:func:`~deepspeed_tpu.ops.attention.ulysses.ulysses_prefill_attention`):
  each rank runs full-chunk attention on a head subset; requires
  heads-per-model-shard % axis size == 0.
* ``"ring"`` — ppermute hops
  (:func:`~deepspeed_tpu.ops.attention.ring.ring_prefill_attention`):
  the prefix seeds the online-softmax carries and the chunk hops the
  ring; any head count rides the axis.

Both land their KV through the standard ``paged_write`` contract in the
model code BEFORE this call — pages in the pool are the source of truth
and everything downstream (decode, prefix-cache donation, COW, spec
verify, handoff) is unchanged.
"""

from deepspeed_tpu.ops.attention.ring import ring_prefill_attention
from deepspeed_tpu.ops.attention.ulysses import ulysses_prefill_attention


def paged_prefill_attention(q, k, v, k_pref, v_pref, prefix_len, mesh, *,
                            axis="sequence", impl="ulysses", scale=None):
    """Distributed chunk-vs-[prefix|chunk] attention.

    q/k/v: [b, L, h, d] the chunk (L shards over ``axis``);
    k_pref/v_pref: [b, maxT, h, d] the paged-pool gather (GQA callers
    expand kv heads to h first); prefix_len: traced scalar count of
    valid prefix rows.  Returns [b, L, h, d], sequence-sharded like q.
    """
    if impl == "ulysses":
        return ulysses_prefill_attention(q, k, v, k_pref, v_pref,
                                         prefix_len, mesh, axis=axis,
                                         scale=scale)
    if impl == "ring":
        return ring_prefill_attention(q, k, v, k_pref, v_pref,
                                      prefix_len, mesh, axis=axis,
                                      scale=scale)
    raise ValueError(f"unknown sequence-parallel impl {impl!r} "
                     "(expected 'ulysses' or 'ring')")
