"""Sequence/context parallelism (the reference's §5.7 gap, filled natively).

``DistributedAttention`` mirrors the name later DeepSpeed gives its Ulysses
layer (deepspeed/sequence/layer.py); here it dispatches to either the
Ulysses all-to-all path or the ring-attention path over the `sequence`
mesh axis.
"""

from deepspeed_tpu.ops.attention.ring import (ring_attention_local,  # noqa: F401
                                              ring_attention_sharded,
                                              ring_prefill_attention)
from deepspeed_tpu.ops.attention.ulysses import (  # noqa: F401
    ulysses_attention_local, ulysses_attention_sharded,
    ulysses_prefill_attention)
from deepspeed_tpu.sequence.prefill import paged_prefill_attention  # noqa: F401


class DistributedAttention:
    """Callable wrapper: DistributedAttention(mesh, impl=...)(q, k, v)."""

    def __init__(self, mesh, *, axis="sequence", impl="ulysses", causal=True,
                 attn_fn=None):
        assert impl in ("ulysses", "ring"), impl
        self.mesh = mesh
        self.axis = axis
        self.impl = impl
        self.causal = causal
        self.attn_fn = attn_fn

    def __call__(self, q, k, v):
        if self.impl == "ring":
            return ring_attention_sharded(q, k, v, self.mesh, axis=self.axis,
                                          causal=self.causal)
        return ulysses_attention_sharded(q, k, v, self.mesh, axis=self.axis,
                                         causal=self.causal,
                                         attn_fn=self.attn_fn)
