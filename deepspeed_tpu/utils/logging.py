"""Logging utilities.

TPU-native equivalent of the reference's ``deepspeed/utils/logging.py``:
a package-level ``logger`` plus ``log_dist(msg, ranks=[...])`` that only
emits on the given process indices (JAX process index, not per-chip rank —
one process drives many chips on TPU).
"""

import logging
import os
import sys

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def create_logger(name="deepspeed_tpu", level=logging.INFO):
    logger_ = logging.getLogger(name)
    if logger_.handlers:
        return logger_
    logger_.setLevel(level)
    logger_.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setLevel(level)
    formatter = logging.Formatter(
        "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s")
    handler.setFormatter(formatter)
    logger_.addHandler(handler)
    return logger_


logger = create_logger(
    level=log_levels.get(os.environ.get("DS_TPU_LOG_LEVEL", "info"), logging.INFO))


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log only on the listed process indices (None or [-1] == all)."""
    my_rank = _process_index()
    if ranks is None or len(ranks) == 0 or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        print(message, flush=True)


def should_log_le(max_log_level_str):
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in log_levels:
        raise ValueError(f"{max_log_level_str} is not one of the `logging` levels")
    return logger.getEffectiveLevel() <= log_levels[max_log_level_str]
