"""Communication logging (reference: ``deepspeed/utils/comms_logging.py``).

``calc_bw_log`` reproduces the reference's algorithmic/bus-bandwidth formulas
(:28): allreduce moves 2(n-1)/n of the message, all_gather/reduce_scatter
(n-1)/n, all_to_all (n-1)/n.
"""

import math

from deepspeed_tpu.utils.logging import log_dist, logger


def get_caller_func(frame=3):
    import sys
    return sys._getframe(frame).f_code.co_name


def convert_size(size_bytes):
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return f"{s} {size_name[i]}"


def calc_bw_log(comm_op, size, duration, n=1):
    """Returns (msg_size_bytes, algo_bw_GBps, bus_bw_GBps)."""
    duration = max(duration, 1e-9)
    n = max(n, 1)
    if comm_op in ("all_to_all_single", "all_to_all"):
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n)
    elif comm_op in ("all_reduce", "psum"):
        tput = size * 2 / duration
        busbw = (size / duration) * (2 * (n - 1) / n)
    elif comm_op in ("send", "recv", "isend", "irecv", "broadcast", "ppermute",
                     "reduce", "gather", "scatter", "barrier"):
        tput = size / duration
        busbw = tput
    else:
        logger.warning(f"Cannot derive BW for unknown op {comm_op}")
        return size, 0.0, 0.0
    # GB/s
    return size, tput / 1e9, busbw / 1e9


class CommsLogger:
    """Accumulates per-op records; ``log_all`` prints a summary table."""

    def __init__(self, config=None):
        from deepspeed_tpu.comm.config import CommsLoggerConfig
        config = config or CommsLoggerConfig()
        self.enabled = config.enabled
        self.prof_all = config.prof_all
        self.prof_ops = config.prof_ops
        self.verbose = config.verbose
        self.debug = config.debug
        self.comms_dict = {}

    def configure(self, config):
        self.enabled = config.enabled
        self.prof_all = config.prof_all
        self.prof_ops = config.prof_ops
        self.verbose = config.verbose
        self.debug = config.debug

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def append(self, raw_name, record_name, latency, msg_size, n=1):
        msg_size, algbw, busbw = calc_bw_log(raw_name, msg_size, latency, n)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                vals = self.comms_dict[record_name][msg_size]
                vals[0] += 1
                vals[1].append(latency)
                vals[2].append(algbw)
                vals[3].append(busbw)
                if len(vals) > 4:
                    vals[4] = n     # ledger_rows reports the LAST-seen
                                    # group size (same op+size over a
                                    # different axis updates it)
            else:
                self.comms_dict[record_name][msg_size] = \
                    [1, [latency], [algbw], [busbw], n]
        else:
            self.comms_dict[record_name] = \
                {msg_size: [1, [latency], [algbw], [busbw], n]}
        if self.verbose:
            log_dist(
                f"rank=? | comm op: {record_name} | time (ms): {latency * 1000:.2f} | "
                f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw * 8:.2f} | "
                f"busbw (Gbps): {busbw * 8:.2f}", ranks=[0])

    def aggregate_events(self):
        """Per-op aggregate ``(tag, value)`` rows for the monitor
        stream (``comm.log_summary`` routing): cumulative call count,
        cumulative message bytes (op-scaled exactly like the printed
        table — ``calc_bw_log`` stores gather/scatter as the full
        buffer), and the mean bus bandwidth, under
        ``comm/<op>/{calls,bytes,busbw_gbps}``."""
        from numpy import mean
        out = []
        for op in self.comms_dict:
            calls = bytes_ = 0
            busbw = []
            for msg_size, vals in self.comms_dict[op].items():
                calls += vals[0]
                bytes_ += msg_size * vals[0]
                busbw.extend(vals[3])
            out.append((f"comm/{op}/calls", calls))
            out.append((f"comm/{op}/bytes", bytes_))
            # same unit as ledger_rows/bench_row (the raw calc_bw_log
            # GB/s figure under the schema's historic field name) so
            # every comm-ledger surface reports one number; only the
            # printed table shows bits (x8)
            out.append((f"comm/{op}/busbw_gbps",
                        round(float(mean(busbw)), 3) if busbw
                        else 0.0))
        return out

    def ledger_rows(self):
        """The accumulator re-expressed as canonical comm-ledger rows
        (comm/telemetry.bench_row schema) — what the benches emit, so
        runtime and offline numbers parse identically."""
        from numpy import mean
        rows = []
        for op in self.comms_dict:
            for msg_size, vals in sorted(self.comms_dict[op].items()):
                # msg_size is already op-scaled by calc_bw_log (gather/
                # scatter record the full buffer), so no re-scaling here
                rows.append({
                    "op": op, "bytes": int(msg_size),
                    "latency_ms": round(float(mean(vals[1])) * 1e3, 4),
                    "algbw_gbps": round(float(mean(vals[2])), 3),
                    "busbw_gbps": round(float(mean(vals[3])), 3),
                    "n": vals[4] if len(vals) > 4 else 1,
                    "calls": vals[0]})
        return rows

    def log_all(self, print_log=True, show_straggler=False):
        from numpy import mean
        lines = [f"{'Comm. Op': <20}{'Message Size': <20}{'Count': <20}"
                 f"{'Total Latency(ms)': <20}{'Avg Latency(ms)': <20}"
                 f"{'tput_avg (Gbps)': <20}{'busbw_avg (Gbps)': <20}"]
        for record_name in self.comms_dict:
            lines.append(record_name)
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count = vals[0]
                total_lat = sum(vals[1]) * 1000
                avg_lat = mean(vals[1]) * 1000
                tput = mean(vals[2]) * 8
                busbw = mean(vals[3]) * 8
                lines.append(
                    f"{' ': <20}{convert_size(msg_size): <20}{count: <20}"
                    f"{total_lat: <20.2f}{avg_lat: <20.2f}{tput: <20.2f}{busbw: <20.2f}")
        out = "\n".join(lines)
        if print_log:
            print(out, flush=True)
        return out
