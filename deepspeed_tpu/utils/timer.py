"""Wall-clock timers and throughput accounting.

TPU-native rework of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` :33, ``ThroughputTimer`` :137). CUDA events do
not exist here; device-synchronized timing is done by blocking on
``jax.block_until_ready`` at timer boundaries when ``synchronized=True``.
"""

import time

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _sync():
    try:
        import jax
        (jax.device_put(0.0) + 0).block_until_ready()
    except Exception:
        pass


class Timer:
    """A single named timer supporting repeated start/stop accumulation."""

    def __init__(self, name, synchronized=False):
        self.name = name
        self.synchronized = synchronized
        self.started = False
        self.start_time = 0.0
        self.elapsed_ = 0.0
        self.count = 0

    def start(self):
        if self.started:
            return
        if self.synchronized:
            _sync()
        self.start_time = time.time()
        self.started = True

    def stop(self, record=True):
        if not self.started:
            return
        if self.synchronized:
            _sync()
        self.elapsed_ += time.time() - self.start_time
        self.count += 1
        self.started = False

    def reset(self):
        self.started = False
        self.elapsed_ = 0.0
        self.count = 0

    def elapsed(self, reset=True):
        elapsed = self.elapsed_
        if self.started:
            elapsed += time.time() - self.start_time
        if reset:
            self.reset()
        return elapsed

    def mean(self):
        return self.elapsed_ / max(1, self.count)


class SynchronizedWallClockTimer:
    """Group of named timers (reference: utils/timer.py:33)."""

    def __init__(self, synchronized=True):
        self.timers = {}
        self.synchronized = synchronized

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = Timer(name, synchronized=self.synchronized)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"DeviceMem: in_use {in_use:.2f} GB, peak {peak:.2f} GB"
        except Exception:
            return "DeviceMem: unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])

    def get_timers(self):
        return self.timers


class NoopTimer:
    class _Inner:
        def start(self):
            pass

        def stop(self, **kwargs):
            pass

        def reset(self):
            pass

        def elapsed(self, **kwargs):
            return 0.0

    def __call__(self, name):
        return self._Inner()

    def log(self, *args, **kwargs):
        pass

    def get_timers(self):
        return {}


class ThroughputTimer:
    """Samples/sec + TFLOPs accounting (reference: utils/timer.py:137).

    With a ``monitor`` whose ``enabled`` flag is truthy, the periodic
    report rides the monitor event stream (``train/samples_per_s`` +
    ``train/samples_per_s_avg``, stepped by global step) instead of the
    bare ``log_dist`` print — same cadence, same numbers, one telemetry
    surface (docs/observability.md taxonomy).  Without one (or with a
    disabled MonitorMaster) the legacy print is preserved byte-for-byte.
    """

    def __init__(self, batch_size, start_step=2, steps_per_output=50,
                 monitor_memory=False, monitor=None,
                 event_prefix="train/"):
        self.monitor = monitor
        self.event_prefix = event_prefix
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self._steps_since_report = 0

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        self.started = True
        if self.global_step_count >= self.start_step:
            # sync only at a measurement-window edge: a device barrier
            # per step would serialize the async dispatch queue (and on
            # relayed devices costs a full host round trip per step);
            # per-step wall deltas still sum to the true window time
            if self.global_step_count == self.start_step:
                _sync()
            self.start_time = time.time()

    def stop(self, global_step=False, report_speed=True, steps=1):
        """``steps`` > 1 credits one start/stop span with that many
        optimizer steps (train_loop's fused multi-step dispatch), keeping
        samples/sec and step-count-driven reporting honest."""
        if not self.started:
            return
        self.started = False
        self.micro_step_count += steps
        if global_step:
            self.global_step_count += steps
        if self.start_time > 0:
            if global_step and \
                    self.global_step_count % self.steps_per_output == 0:
                _sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self.start_time = 0
            if global_step:
                self._steps_since_report += steps
                if report_speed and \
                        self.global_step_count % self.steps_per_output == 0:
                    # current rate over the whole window since the last
                    # report: with sync only at window edges, a single
                    # step's delta would absorb the async queue drain
                    window = self.batch_size * self._steps_since_report
                    curr = window / self.step_elapsed_time
                    avg = self.avg_samples_per_sec()
                    if self.monitor is not None and \
                            getattr(self.monitor, "enabled", True):
                        events = [(self.event_prefix + "samples_per_s",
                                   float(curr), self.global_step_count)]
                        if avg > float("-inf"):
                            events.append(
                                (self.event_prefix + "samples_per_s_avg",
                                 float(avg), self.global_step_count))
                        self.monitor.write_events(events)
                    else:
                        log_dist(
                            f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                            f"global_step={self.global_step_count}, "
                            f"RunningAvgSamplesPerSec={avg:.4f}, "
                            f"CurrSamplesPerSec={curr:.4f}",
                            ranks=[0])
                    self.step_elapsed_time = 0
                    self._steps_since_report = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-inf")
