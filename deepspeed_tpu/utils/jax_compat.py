"""Compatibility shims across jax generations.

The codebase targets current jax (``jax.shard_map`` with ``check_vma``);
older runtimes (< 0.5) only ship ``jax.experimental.shard_map.shard_map``
with the ``check_rep`` spelling. Installing the alias once here (imported
from the package ``__init__``) keeps every call site — including tests —
on the one modern spelling instead of scattering try/except imports.
"""

import jax

# True when this runtime predates the native jax.shard_map (< 0.5): the
# shim below keeps code RUNNING, but the legacy replication checker
# cannot statically infer replicated outputs (its transpose then inserts
# a spurious psum), so grad-exactness tests against replicated-out
# shard_maps are skipped on such runtimes (see tests/unit/pipe).
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")


def pinned_host_available():
    """Whether the default device exposes a pinned_host memory space
    (host-offload tests need it; CPU runtimes before 0.5 only have
    unpinned_host)."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:
        return False
    return "pinned_host" in kinds


def install():
    from jax import lax
    if not hasattr(lax, "axis_size"):
        # lax.axis_size(name) arrived with the new shard_map; the legacy
        # axis_frame(name) returns exactly the static int size
        lax.axis_size = jax.core.axis_frame

    if not hasattr(jax, "typeof"):
        # jax.typeof (aval introspection, used for varying-manual-axes
        # plumbing) arrived with the new shard_map; the old aval has no
        # .vma attribute, which call sites already treat as frozenset()
        jax.typeof = jax.core.get_aval

    if hasattr(jax, "shard_map"):
        return

    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        # old API names the replication check `check_rep`; its legacy
        # checker also rejects valid programs the new vma machinery
        # accepts (e.g. cond branches inside the ring-attention scan),
        # so it defaults OFF here — it is a diagnostics pass, numerics
        # are unaffected
        kw["check_rep"] = False if check_vma is None else check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map
