"""Memory diagnostics (reference ``runtime/utils.py:770``
``see_memory_usage`` / ``:721`` ``memory_status`` — CUDA
allocated/reserved prints). TPU form: per-device HBM stats from the
runtime's ``memory_stats()`` plus host RSS."""

import os

import jax

from deepspeed_tpu.utils.logging import logger


def _gb(n):
    return f"{n / (1024 ** 3):.2f} GB"


def device_memory_stats(device=None):
    """{bytes_in_use, peak_bytes_in_use, bytes_limit} for one device
    (zeros when the backend reports nothing, e.g. CPU)."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
    }


def host_memory_rss():
    """Resident set size of this process in bytes (no psutil needed)."""
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def see_memory_usage(message, force=False, ranks=(0,)):
    """Log HBM + host memory (reference see_memory_usage contract: called
    at phase boundaries, gated by a force flag)."""
    if not force:
        return
    if jax.process_index() not in ranks:
        return
    parts = [message]
    for i, dev in enumerate(jax.local_devices()):
        s = device_memory_stats(dev)
        if s["bytes_limit"]:
            parts.append(
                f"dev{i}: {_gb(s['bytes_in_use'])} in use "
                f"(peak {_gb(s['peak_bytes_in_use'])}, "
                f"limit {_gb(s['bytes_limit'])})")
    parts.append(f"host RSS: {_gb(host_memory_rss())}")
    logger.info(" | ".join(parts))


def memory_status(tag=""):
    """Compact dict for programmatic checks (used by offload tests to
    assert HBM headroom)."""
    s = device_memory_stats()
    return {"tag": tag, **s, "host_rss": host_memory_rss()}
