"""Environment / op-compatibility report (``ds_report``).

Reference: ``deepspeed/env_report.py:1`` — the op compatibility table plus
framework/hardware versions printed by ``bin/ds_report``.
"""

import importlib
import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def op_report_rows():
    from deepspeed_tpu.ops.op_builder import op_report
    return op_report()


def main(args=None):
    import jax

    import deepspeed_tpu

    print("-" * 64)
    print("DeepSpeed-TPU C++ op report")
    print("-" * 64)
    print(f"{'op name':20} {'compatible':12} {'built'}")
    for name, compatible, installed in op_report_rows():
        print(f"{name:20} {GREEN_OK if compatible else RED_NO:12} "
              f"{GREEN_OK if installed else '[not built]'}")
    print("-" * 64)
    print("General environment:")
    print(f"{'python':24} {sys.version.split()[0]}")
    print(f"{'deepspeed_tpu':24} {deepspeed_tpu.__version__}")
    print(f"{'jax':24} {jax.__version__}")
    for mod in ("flax", "optax", "numpy"):
        try:
            m = importlib.import_module(mod)
            print(f"{mod:24} {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod:24} not installed")
    try:
        devs = jax.devices()
        print(f"{'platform':24} {devs[0].platform}")
        print(f"{'device kind':24} {getattr(devs[0], 'device_kind', '?')}")
        print(f"{'device count':24} {len(devs)}")
        from deepspeed_tpu.accelerator import get_accelerator
        acc = get_accelerator()
        print(f"{'accelerator':24} {acc.device_name()}")
        print(f"{'comm backend':24} {acc.communication_backend_name()}")
    except Exception as e:  # no backend in exotic CI
        print(f"{'platform':24} unavailable ({e})")
    print("-" * 64)
    return 0


if __name__ == "__main__":
    sys.exit(main())
