"""Elastic training: batch-size math compatible with many device counts.

Reference: ``deepspeed/elasticity/elasticity.py`` (``compute_elastic_config``
:233, candidate generation :27-125) and ``elasticity/config.py``. The math
is framework-agnostic (SURVEY.md §5.3 "ports for free"): choose a global
batch size — built from the allowed micro-batch sizes scaled by
highly-composite multipliers — that is divisible across as many device
counts as possible, so a preempted/regrown TPU slice can resume without
changing the effective batch.
"""

from deepspeed_tpu.elasticity.elasticity import (  # noqa: F401
    ElasticityConfig, ElasticityConfigError, ElasticityError,
    ElasticityIncompatibleWorldSize, compute_elastic_config,
    get_compatible_device_counts)
