"""Elastic agent: monitor workers, re-rendezvous on failure, resume.

Reference: ``deepspeed/elasticity/elastic_agent.py:28`` (``DSElasticAgent``
subclassing torch.elastic's ``LocalElasticAgent``: rendezvous + worker
monitoring + restart with DS env injected) and the elastic branch of
``launcher/launch.py``.

TPU shape: one agent per node supervises the node's worker processes.
On any worker failure the agent tears the group down (a jax.distributed
collective cannot survive a lost participant), picks a fresh coordinator
port, and relaunches every worker with ``DS_ELASTIC_RESTART_COUNT``
bumped. Recovery of *state* is checkpoint-based (SURVEY §5.3: the real
fault-tolerance story): training scripts call ``load_checkpoint`` at
startup, which no-ops on the first launch (no ``latest`` yet) and
resumes after a restart.

The graceful-shutdown contract with ``resilience.ResilientTrainer``:
``_terminate`` sends SIGTERM first and escalates to SIGKILL only after
``term_grace_s`` — a supervised worker uses that window to finish its
in-flight step and write the preemption checkpoint
(``DS_PREEMPTION_GRACE_S`` in the worker env carries the budget), so an
agent-driven restart resumes from the step it was killed at, not from
the last periodic save.
"""

import os
import signal
import socket
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class DSElasticAgent:
    """Supervise one node's workers with restart-on-failure.

    Args mirror the per-node launcher (launch.py): the agent owns worker
    spawning so it can re-rendezvous the whole group on a new port.
    """

    def __init__(self, training_script, script_args=(), num_workers=1,
                 num_nodes=1, node_rank=0, master_addr="127.0.0.1",
                 master_port=None, max_restarts=3, monitor_interval=0.25,
                 force_cpu_devices=0, rdzv_port=None, term_grace_s=10.0):
        self.training_script = training_script
        self.script_args = list(script_args)
        self.num_workers = num_workers
        self.num_nodes = num_nodes
        self.node_rank = node_rank
        self.master_addr = master_addr
        self.master_port = master_port or _free_port()
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.force_cpu_devices = force_cpu_devices
        self.rdzv_port = rdzv_port
        # SIGTERM-to-SIGKILL budget: a worker wrapped in
        # resilience.ResilientTrainer uses this window to finish its
        # in-flight step and write the preemption checkpoint. Published
        # to workers as DS_PREEMPTION_GRACE_S so the trainer can size
        # its final save against the real budget.
        self.term_grace_s = float(term_grace_s)
        self.restart_count = 0
        self._procs = []
        self._store = None
        self._rdzv = None

    # ----------------------------------------------------------- workers
    def _spawn(self):
        world_size = self.num_nodes * self.num_workers
        self._procs = []
        for local_rank in range(self.num_workers):
            rank = self.node_rank * self.num_workers + local_rank
            env = os.environ.copy()
            env.update({
                "COORDINATOR_ADDRESS":
                    f"{self.master_addr}:{self.master_port}",
                "NUM_PROCESSES": str(world_size),
                "PROCESS_ID": str(rank),
                "RANK": str(rank),
                "LOCAL_RANK": str(local_rank),
                "WORLD_SIZE": str(world_size),
                "MASTER_ADDR": self.master_addr,
                "MASTER_PORT": str(self.master_port),
                "DS_ELASTIC_RESTART_COUNT": str(self.restart_count),
                "DS_PREEMPTION_GRACE_S": str(self.term_grace_s),
            })
            if self.force_cpu_devices:
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "") +
                    " --xla_force_host_platform_device_count="
                    f"{self.force_cpu_devices}")
            cmd = [sys.executable, self.training_script] + self.script_args
            self._procs.append(subprocess.Popen(cmd, env=env))
        logger.info(f"elastic agent: spawned {self.num_workers} workers "
                    f"(attempt {self.restart_count}, "
                    f"port {self.master_port})")

    def _terminate(self):
        # graceful first: SIGTERM is the preemption notice the
        # resilience supervisor turns into a boundary checkpoint; only
        # after term_grace_s does escalation to SIGKILL destroy state
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + self.term_grace_s
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    @staticmethod
    def _classify(states, epoch_advanced):
        """Deterministic monitor classification — a pure function of
        the observed process states plus the epoch flag, so no
        interleaving of a worker exit with the epoch watch can flip the
        answer.

        Priority:

        1. all exited 0 -> ``ok`` (never touch the store after a clean
           local finish — the node-0 agent may already be tearing it
           down during a skewed shutdown);
        2. any nonzero exit -> ``failed``: the local rc is ground
           truth.  This includes deaths *caused by* a peer restart
           (coordinator vanished): the old ordering preferred
           ``peer_restart`` whenever the epoch had advanced, which
           misclassified a genuine local failure as a peer event when
           a peer's bump landed between the state poll and the epoch
           read — losing the rc and the failure log line.  Reporting
           ``failed`` is always safe: ``signal_restart(from_epoch)``
           is a compare-and-swap, so a bump for a round a peer
           already advanced is a no-op and the budget burns exactly
           one round either way;
        3. epoch advanced with locals still running (or exiting 0
           under teardown skew) -> ``peer_restart``;
        4. otherwise -> keep polling.

        Returns ("ok"|"failed"|"peer_restart"|None, rc)."""
        if all(rc == 0 for rc in states):
            return "ok", 0
        bad = [rc for rc in states if rc is not None and rc != 0]
        if bad:
            return "failed", bad[0]
        if epoch_advanced:
            return "peer_restart", 0
        return None, 0

    def _monitor(self, watch_epoch=None):
        """Block until the group finishes, a worker dies, or (multi-node)
        the rendezvous epoch advances because ANOTHER node's worker
        died. Returns ("ok", 0) | ("failed", rc) | ("peer_restart", 0).
        Classification is delegated to :meth:`_classify` — see its
        docstring for the determinism contract."""
        while True:
            states = [p.poll() for p in self._procs]
            advanced = self._rdzv is not None and \
                self._rdzv.current_epoch() != watch_epoch
            state, rc = self._classify(states, advanced)
            if state is not None:
                return state, rc
            time.sleep(self.monitor_interval)

    # --------------------------------------------------------------- run
    def run(self):
        """Supervise until success or restart budget exhausted; returns
        the exit code (0 = the whole group finished cleanly).

        Multi-node: agents coordinate through the node-0 agent's
        rendezvous store (elasticity/rendezvous.py, reference torch
        store-based rendezvous) — a worker loss on ANY node bumps the
        epoch, every agent tears down and re-joins, and node 0 publishes
        the new coordinator port for the round."""
        handled = {}
        for sig in (signal.SIGINT, signal.SIGTERM):
            handled[sig] = signal.signal(
                sig, lambda s, f: (self._terminate(), sys.exit(128 + s)))
        try:
            if self.num_nodes > 1:
                return self._run_multinode()
            while True:
                self._spawn()
                state, rc = self._monitor()
                if state == "ok":
                    return 0
                logger.warning(
                    f"elastic agent: worker failed (rc={rc}) on attempt "
                    f"{self.restart_count}")
                self._terminate()
                if self.restart_count >= self.max_restarts:
                    logger.error(
                        f"elastic agent: restart budget "
                        f"({self.max_restarts}) exhausted")
                    return rc
                self.restart_count += 1
                # a fresh port forces a clean re-rendezvous: the old
                # coordinator's listening socket dies with its process
                self.master_port = _free_port()
        finally:
            for sig, old in handled.items():
                signal.signal(sig, old)
            if self._store is not None:
                self._store.close()

    def _run_multinode(self):
        from deepspeed_tpu.elasticity.rendezvous import (
            ElasticRendezvous, RendezvousClient, RendezvousStore)
        assert self.rdzv_port, \
            "multi-node elastic needs rdzv_port (the node-0 agent's " \
            "rendezvous store port, shared by every agent)"
        if self.node_rank == 0:
            self._store = RendezvousStore(port=self.rdzv_port)
        client = RendezvousClient(self.master_addr, self.rdzv_port)
        self._rdzv = ElasticRendezvous(client, self.node_rank,
                                       self.num_nodes, self.master_addr)
        try:
            return self._multinode_loop()
        finally:
            # never orphan workers: a store outage (node-0 host died)
            # raises out of _monitor/next_round — the local training
            # processes must die with the agent, not wedge on dead
            # collectives holding the chips
            self._terminate()

    def _multinode_loop(self):
        last_rc = 1
        min_epoch = 0
        while True:
            epoch, port = self._rdzv.next_round(min_epoch=min_epoch)
            min_epoch = epoch + 1   # never re-join a finished round
            if epoch > self.max_restarts:
                logger.error(f"elastic agent[{self.node_rank}]: restart "
                             f"budget ({self.max_restarts}) exhausted")
                return last_rc
            self.restart_count = epoch
            self.master_port = port
            self._spawn()
            state, rc = self._monitor(watch_epoch=epoch)
            if state == "ok":
                # barrier before the node-0 agent closes the store:
                # peers may still be mid-shutdown polling the epoch.
                # Once OUR workers exited 0 the run is a success no
                # matter what the store does — a peer's skewed shutdown
                # (store closed early, barrier timeout) must not turn a
                # clean finish into a nonzero exit (r4 advisor finding).
                try:
                    if not self._rdzv.signal_done():
                        logger.warning(
                            f"elastic agent[{self.node_rank}]: clean-"
                            "exit barrier timed out (peers still "
                            "shutting down); exiting 0 regardless")
                except Exception as e:
                    logger.warning(
                        f"elastic agent[{self.node_rank}]: store "
                        f"unreachable during clean shutdown ({e}); "
                        "local workers finished — exiting 0")
                return 0
            self._terminate()
            if state == "failed":
                last_rc = rc
                new_epoch = self._rdzv.signal_restart(from_epoch=epoch)
                logger.warning(
                    f"elastic agent[{self.node_rank}]: worker failed "
                    f"(rc={rc}); restart round is now {new_epoch}")
            else:
                logger.warning(
                    f"elastic agent[{self.node_rank}]: peer node "
                    "restarted the group; re-joining")
