"""Elastic batch-size computation (reference elasticity.py:27-290)."""

import math

# Highly-composite numbers: scaling a base micro-batch by one of these
# maximizes the number of divisors (= compatible device counts)
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
            45360, 50400, 55440, 83160, 110880, 166320, 221760, 277200,
            332640, 498960, 554400, 665280]

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Typed view of the config's "elasticity" section (reference
    elasticity/config.py)."""

    def __init__(self, param_dict):
        self.enabled = bool(param_dict.get("enabled", False))
        if not self.enabled:
            return
        if "max_train_batch_size" not in param_dict:
            raise ElasticityConfigError(
                "elasticity needs max_train_batch_size")
        if "micro_batch_sizes" not in param_dict:
            raise ElasticityConfigError("elasticity needs micro_batch_sizes")
        self.max_acceptable_batch_size = int(
            param_dict["max_train_batch_size"])
        self.micro_batches = [int(m) for m in param_dict["micro_batch_sizes"]]
        if any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive: {self.micro_batches}")
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", -1))
        if self.min_gpus < 1 or (self.max_gpus != -1 and
                                 self.max_gpus < self.min_gpus):
            raise ElasticityConfigError(
                f"bad device range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = int(param_dict.get("min_time", 0))
        self.version = float(param_dict.get("version", 0.2))
        self.prefer_larger_batch_size = bool(
            param_dict.get("prefer_larger_batch", True))
        self.ignore_non_elastic_batch_info = bool(
            param_dict.get("ignore_non_elastic_batch_info", False))
        self.model_parallel_size = int(
            param_dict.get("model_parallel_size", 1))
        self.num_gpus_per_node = int(param_dict.get("num_gpus_per_node", 1))


def _candidate_batch_sizes(micro_batches, max_batch):
    """Each micro-batch (and their lcm) scaled by the largest HCN that
    keeps the product under max_batch."""
    bases = sorted(set(micro_batches) | {math.lcm(*micro_batches)})
    out = set()
    for base in bases:
        if base >= max_batch:
            out.add(base)
            continue
        limit = max_batch // base
        hcn = max(h for h in HCN_LIST if h <= limit)
        out.add(hcn * base)
    return sorted(out)


def get_compatible_device_counts(batch_size, micro_batches, min_devs,
                                 max_devs):
    """All device counts n such that some micro-batch m gives
    batch_size == m * gas * n for integer gas (reference get_valid_gpus)."""
    valid = set()
    for m in micro_batches:
        if batch_size % m:
            continue
        slots = batch_size // m   # n * gas
        for n in range(1, slots + 1):
            if slots % n == 0 and min_devs <= n <= max_devs:
                valid.add(n)
    return sorted(valid)


def _best_candidate(candidates, micro_batches, min_devs, max_devs,
                    prefer_larger):
    best = (len(micro_batches) and min(micro_batches)) or 1
    best_valid = []
    for bs in candidates:
        valid = get_compatible_device_counts(bs, micro_batches, min_devs,
                                             max_devs)
        better = len(valid) > len(best_valid) or (
            len(valid) == len(best_valid) and
            ((prefer_larger and bs > best) or
             (not prefer_larger and bs < best)))
        if better:
            best, best_valid = bs, valid
    return best, best_valid


def compute_elastic_config(ds_config, target_deepspeed_version=None,
                           world_size=0, return_microbatch=False):
    """(final_batch_size, valid_device_counts[, micro_batch]) for the
    config's elasticity section (reference compute_elastic_config :233).

    With ``world_size`` given, also checks compatibility and computes the
    per-device micro batch (largest allowed micro-batch whose
    micro*gas*world == final_batch)."""
    cfg = ds_config if isinstance(ds_config, ElasticityConfig) else \
        ElasticityConfig(ds_config.get("elasticity", ds_config))
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity section not enabled")
    max_devs = cfg.max_gpus if cfg.max_gpus != -1 else \
        cfg.max_acceptable_batch_size // min(cfg.micro_batches)
    if any(m > cfg.max_acceptable_batch_size for m in cfg.micro_batches):
        raise ElasticityConfigError(
            "every micro batch must be <= max_train_batch_size")

    candidates = _candidate_batch_sizes(cfg.micro_batches,
                                        cfg.max_acceptable_batch_size)
    final_batch, valid = _best_candidate(
        candidates, cfg.micro_batches, cfg.min_gpus, max_devs,
        cfg.prefer_larger_batch_size)
    if not valid:
        # refuse configs with no compatible device count rather than hand
        # back an unusable fallback batch (reference raises the same way)
        raise ElasticityError(
            f"no candidate batch size in {candidates} is compatible with "
            f"any device count in [{cfg.min_gpus}, {max_devs}] for "
            f"micro_batches {cfg.micro_batches}")

    # valid counts are DATA-PARALLEL replica counts: with model
    # parallelism, the device world divides into world/mp replicas
    # (reference v0.2 semantics)
    dp_size = world_size
    if world_size > 0 and cfg.model_parallel_size > 1:
        if world_size % cfg.model_parallel_size:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not divisible by "
                f"model_parallel_size {cfg.model_parallel_size}")
        dp_size = world_size // cfg.model_parallel_size
    if world_size > 0 and dp_size not in valid:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} (data-parallel {dp_size}) is not "
            f"compatible with batch {final_batch} (valid counts: {valid})")

    if not return_microbatch:
        return final_batch, valid
    assert world_size > 0, "return_microbatch needs world_size"
    micro = None
    for m in sorted(cfg.micro_batches,
                    reverse=cfg.prefer_larger_batch_size):
        if final_batch % (m * dp_size) == 0:
            micro = m
            break
    return final_batch, valid, micro
