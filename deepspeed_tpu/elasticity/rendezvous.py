"""Cross-node elastic rendezvous: a tiny TCP KV store + epoch protocol.

Reference: the torch.elastic store-based rendezvous DSElasticAgent
inherits (``deepspeed/elasticity/elastic_agent.py:28`` — c10d store,
epoch/round counters, member barriers). TPU shape: agents (one per
node) coordinate restarts through this store; the jax.distributed
coordinator the WORKERS use is a separate, per-epoch throwaway whose
port is agreed here.

Protocol (all keys live in the store hosted by the node-0 agent, which
survives worker crashes because the agent owns it, not the workers):

* ``epoch``      — monotonically increasing restart round. Any agent
  that sees a dead local worker bumps it; agents watching the value see
  the bump and tear their own workers down (the cross-node signal the
  single-node design lacked, VERDICT r3 weak #5).
* ``joined:{e}`` — member counter for round e. Agents spawn only after
  every node joined the SAME round; a straggler that joined a stale
  round re-joins at the current one.
* ``port:{e}``   — the round's jax.distributed coordinator port, chosen
  and published by node 0.

The store speaks one JSON object per line: {"op": "get"|"set"|"add",
"key": k, "value": v} -> {"ok": true, "value": v}.
"""

import json
import socket
import socketserver
import threading
import time

from deepspeed_tpu.utils.logging import logger


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.store
        for line in self.rfile:
            try:
                req = json.loads(line)
                with store.lock:
                    if req["op"] == "set":
                        store.data[req["key"]] = req["value"]
                        val = req["value"]
                    elif req["op"] == "add":
                        # 'add' is NOT idempotent, and the client retries
                        # after connection errors — dedupe by the
                        # client-supplied txn id so a retried add applies
                        # exactly once
                        txn = req.get("txn")
                        if txn is not None and txn in store.applied:
                            val = store.applied[txn]
                        else:
                            val = (store.data.get(req["key"], 0)
                                   + req["value"])
                            store.data[req["key"]] = val
                            if txn is not None:
                                store.applied[txn] = val
                                while len(store.applied) > 4096:
                                    store.applied.pop(
                                        next(iter(store.applied)))
                    elif req["op"] == "cas":
                        # compare-and-swap: succeed only from the
                        # expected old value (epoch bumps use this so
                        # concurrent failure signals advance ONE round)
                        cur = store.data.get(req["key"], 0)
                        if cur == req["old"]:
                            store.data[req["key"]] = req["value"]
                        val = store.data.get(req["key"], 0)
                    else:
                        val = store.data.get(req["key"])
                self.wfile.write(
                    (json.dumps({"ok": True, "value": val}) + "\n")
                    .encode())
                self.wfile.flush()
            except (json.JSONDecodeError, KeyError) as e:
                self.wfile.write(
                    (json.dumps({"ok": False, "error": str(e)}) + "\n")
                    .encode())
                self.wfile.flush()


class RendezvousStore:
    """Threaded TCP KV server; ``with RendezvousStore(port) as s: ...``"""

    def __init__(self, port=0, host="0.0.0.0"):
        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Srv((host, port), _Handler)
        self._srv.store = self
        self.data = {}
        self.applied = {}     # txn id -> result (add dedupe)
        self.lock = threading.Lock()
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        logger.info(f"rendezvous store listening on :{self.port}")

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RendezvousClient:
    """Line-protocol client with reconnect-on-error (the store may come
    up after the client on non-zero nodes)."""

    def __init__(self, host, port, timeout=60.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock = None
        self._file = None

    def _connect(self):
        deadline = time.time() + self.timeout
        while True:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=5)
                self._file = self._sock.makefile("rb")
                return
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rendezvous store at {self.host}:{self.port} "
                        f"unreachable for {self.timeout}s")
                time.sleep(0.2)

    def _call(self, op, key, value=None, old=None, txn=None):
        for attempt in (0, 1):
            if self._sock is None:
                self._connect()
            try:
                req = {"op": op, "key": key}
                if value is not None:
                    req["value"] = value
                if old is not None:
                    req["old"] = old
                if txn is not None:
                    req["txn"] = txn
                self._sock.sendall((json.dumps(req) + "\n").encode())
                resp = json.loads(self._file.readline())
                if not resp.get("ok"):
                    # a server-reported protocol error is not retryable
                    raise RuntimeError(f"rendezvous store rejected "
                                       f"{op} {key}: {resp}")
                return resp.get("value")
            except (OSError, json.JSONDecodeError):
                self.close()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def get(self, key):
        return self._call("get", key)

    def set(self, key, value):
        return self._call("set", key, value)

    def add(self, key, delta=1):
        # txn id makes the retry-after-reconnect exactly-once
        import uuid
        return self._call("add", key, delta, txn=uuid.uuid4().hex)

    def cas(self, key, old, new):
        """Set key to new iff it currently equals old; returns the
        post-call value either way (idempotent under retry)."""
        return self._call("cas", key, new, old=old)

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._file = None


class ElasticRendezvous:
    """The agent-facing epoch protocol over a RendezvousClient."""

    def __init__(self, client, node_rank, num_nodes, master_addr):
        self.c = client
        self.node_rank = node_rank
        self.num_nodes = num_nodes
        self.master_addr = master_addr

    def current_epoch(self):
        return int(self.c.get("epoch") or 0)

    def signal_restart(self, from_epoch=None):
        """A local worker died during round ``from_epoch``: open the
        next round. Compare-and-swap, so CONCURRENT failure signals for
        the same round (node B's workers die because node A's
        coordinator vanished) advance the epoch exactly once instead of
        burning two rounds of the restart budget. Returns the current
        epoch after the call."""
        if from_epoch is None:
            from_epoch = self.current_epoch()
        return int(self.c.cas("epoch", from_epoch, from_epoch + 1))

    def signal_done(self, timeout=30.0):
        """Clean-exit barrier: count this agent done and wait (bounded)
        for every agent, so the node-0 agent doesn't tear the store down
        while peers still poll it mid-shutdown."""
        self.c.add("done", 1)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if int(self.c.get("done") or 0) >= self.num_nodes:
                return True
            time.sleep(0.1)
        return False

    def next_round(self, timeout=120.0, min_epoch=0):
        """Join the current round and block until every node has joined
        it and the coordinator port is published. Returns (epoch, port).
        If the epoch advances while waiting (another node failed during
        join), re-joins at the new one.

        ``min_epoch`` fences ordering on re-joins: an agent that just
        finished round e passes ``min_epoch=e+1`` so it cannot re-join a
        stale round before the failure signal lands in the store
        (joining the same epoch twice would overwrite the round's port
        and strand the peers)."""
        deadline = time.time() + timeout
        while True:
            e = self.current_epoch()
            if e < min_epoch:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rendezvous: epoch stuck at {e} < required "
                        f"{min_epoch} after {timeout}s")
                time.sleep(0.1)
                continue
            self.c.add(f"joined:{e}", 1)
            if self.node_rank == 0:
                # node 0 hosts the jax.distributed coordinator: pick a
                # fresh port there and publish it for this round
                with socket.socket() as s:
                    s.bind(("", 0))
                    port = s.getsockname()[1]
                self.c.set(f"port:{e}", port)
            while True:
                # COMPLETION before staleness: once every node joined e
                # and the port is published, round e happened — return
                # it even if a fast peer already finished e and bumped
                # the epoch for the NEXT round. (Checking the epoch
                # first misclassified a completed round as stale, made
                # this agent rejoin one round ahead of its peers, and
                # wedged the group a round apart — the flake both
                # rendezvous tests exhibited under load.)
                joined = int(self.c.get(f"joined:{e}") or 0)
                port = self.c.get(f"port:{e}")
                if joined >= self.num_nodes and port is not None:
                    return e, int(port)
                cur = self.current_epoch()
                if cur != e:
                    break        # abandoned mid-join; rejoin at cur
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rendezvous round {e}: {joined}/"
                        f"{self.num_nodes} nodes joined after {timeout}s")
                time.sleep(0.1)
