"""Pallas TPU KV-cache decode attention (the `softmax_context` kernel).

TPU-native replacement for the reference's inference attention kernel
(csrc/transformer/inference/csrc/pt_binding.cpp `softmax_context`,
`inference_context.h` KV workspace): single-token queries attend over a
device-resident cache buffer without materializing [heads, max_len]
score tensors in HBM, with additive bias (position mask, ALiBi).

Design:
  * caches stay in their storage layout [batch, max_len, kv_heads, dim] —
    BlockSpecs index directly into it, no transpose copies per token.
  * grid = (batch, kv_heads, k_blocks); the k axis is innermost so the
    online-softmax state lives in VMEM scratch across grid steps
    (same scheme as ops/attention/flash.py).
  * GQA is native: each kv head's grid step loads its whole group of
    query heads ([group, dim] block), so grouped caches are never
    expanded to num_heads (the `_repeat_kv` copy disappears).
  * bias [batch, heads, 1, max_len] carries the validity mask (slots past
    the write index) and any ALiBi term; fp32 statistics throughout.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import NEG_INF, _pick_block


def _decode_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, nk):
    """One grid step: ALL heads against one kv block. Blocks span the
    full head dims (equal-to-array, so any head count satisfies the TPU
    (8,128) tiling rule), and the per-head products use dot_general
    batch dims directly on the cache's storage layout — Mosaic rejects
    both the reshape ([h,d]->[kv,grp,d], "unsupported shape cast") and
    per-head sub-8 blocks, so no reshapes or transposes appear here."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    q = q_ref[0]                                          # [h, 1, d]
    k = k_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    v = v_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    # leading-batch dot over heads (Mosaic supports batch dims only at
    # position 0 on both sides — hence q pre-shaped [h, 1, d] outside)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale       # [h, 1, bk]
    s = s + bias_ref[0]                                   # [h, 1, bk]
    s = jnp.maximum(s, NEG_INF)  # keep masked slots finite (see flash.py)

    m_prev = m_scr[:h, :1]
    l_prev = l_scr[:h, :1]
    m_cur = jnp.max(s, axis=2)                            # [h, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    row_live = m_new > NEG_INF / 2
    alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
    p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=2)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)               # [h, 1, d]
    acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
    m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
    l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _decode_pallas(q, k_cache, v_cache, bias, *, scale, block_k, interpret):
    b, one, h, d = q.shape
    max_len, kv_h = k_cache.shape[1], k_cache.shape[2]
    if kv_h != h:
        # GQA: expand the cache to full heads for the kernel (the
        # per-kv-head block formulation violates the (8,128) tiling rule
        # for small groups); the expansion costs grp x cache traffic,
        # still a net win over materializing [h, max_len] scores
        k_cache = _repeat_kv(k_cache, h // kv_h)
        v_cache = _repeat_kv(v_cache, h // kv_h)
    nk = max_len // block_k
    scr_rows = max(h, 8)   # TPU sublane tile
    # q enters as [b, h, 1, d]: the kernel needs the head dim leading
    # for Mosaic's batch-dim-0 dot rule (the [h, d] -> [kv, grp, d]
    # reshape of the head dim is an unsupported shape cast in-kernel)
    q_t = q.transpose(0, 2, 1, 3)

    kernel = functools.partial(_decode_kernel, scale=scale, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(b, nk),
        in_specs=[
            pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, h, 1, block_k), lambda ib, j: (ib, 0, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_t, k_cache, v_cache, bias)
    return out.transpose(0, 2, 1, 3)                      # [b, 1, h, d]


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, l, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None], (b, l, h, n_rep, d)) \
        .reshape(b, l, h * n_rep, d)


def decode_attention(q, k_cache, v_cache, *, bias, scale=None,
                     interpret=None, block_k=None):
    """Attention of `q` [b, l, heads, d] over a cache buffer
    [b, max_len, kv_heads, d] with additive `bias` (broadcastable to
    [b, heads, l, max_len]) carrying the validity mask.

    Single-token decode (l == 1) runs the Pallas kernel; multi-token
    (prefill into a cache) falls back to the jnp oracle. GQA caches
    (kv_heads < heads) are consumed directly by the kernel.
    """
    from deepspeed_tpu.ops.attention.reference import mha_reference

    b, l, h, d = q.shape
    kv_h = k_cache.shape[2]
    max_len = k_cache.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if l == 1 and h % kv_h == 0 and max_len % (block_k or 128) == 0:
        block_k = block_k or _pick_block(max_len)
        bias_full = jnp.broadcast_to(
            bias.astype(jnp.float32), (b, h, 1, max_len))
        return _decode_pallas(q, k_cache, v_cache, bias_full, scale=scale,
                              block_k=block_k, interpret=interpret)

    k_full = _repeat_kv(k_cache, h // kv_h)
    v_full = _repeat_kv(v_cache, h // kv_h)
    return mha_reference(q, k_full, v_full, causal=False, bias=bias,
                         scale=scale)
