"""Pallas TPU KV-cache decode attention (the `softmax_context` kernel).

TPU-native replacement for the reference's inference attention kernel
(csrc/transformer/inference/csrc/pt_binding.cpp `softmax_context`,
`inference_context.h` KV workspace): single-token queries attend over a
device-resident cache buffer without materializing [heads, max_len]
score tensors in HBM, with additive bias (position mask, ALiBi).

Design:
  * caches stay in their storage layout [batch, max_len, kv_heads, dim] —
    BlockSpecs index directly into it, no transpose copies per token.
  * grid = (batch, kv_heads, k_blocks); the k axis is innermost so the
    online-softmax state lives in VMEM scratch across grid steps
    (same scheme as ops/attention/flash.py).
  * GQA is native: each kv head's grid step loads its whole group of
    query heads ([group, dim] block), so grouped caches are never
    expanded to num_heads (the `_repeat_kv` copy disappears).
  * bias [batch, heads, 1, max_len] carries the validity mask (slots past
    the write index) and any ALiBi term; fp32 statistics throughout.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import NEG_INF, _pick_block


def _decode_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, nk):
    """One grid step: ALL heads against one kv block. Blocks span the
    full head dims (equal-to-array, so any head count satisfies the TPU
    (8,128) tiling rule), and the per-head products use dot_general
    batch dims directly on the cache's storage layout — Mosaic rejects
    both the reshape ([h,d]->[kv,grp,d], "unsupported shape cast") and
    per-head sub-8 blocks, so no reshapes or transposes appear here."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    q = q_ref[0]                                          # [h, 1, d]
    k = k_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    v = v_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    # leading-batch dot over heads (Mosaic supports batch dims only at
    # position 0 on both sides — hence q pre-shaped [h, 1, d] outside)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale       # [h, 1, bk]
    s = s + bias_ref[0]                                   # [h, 1, bk]
    s = jnp.maximum(s, NEG_INF)  # keep masked slots finite (see flash.py)

    m_prev = m_scr[:h, :1]
    l_prev = l_scr[:h, :1]
    m_cur = jnp.max(s, axis=2)                            # [h, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    row_live = m_new > NEG_INF / 2
    alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
    p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=2)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)               # [h, 1, d]
    acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
    m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
    l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _decode_pallas(q, k_cache, v_cache, bias, *, scale, block_k, interpret):
    b, one, h, d = q.shape
    max_len, kv_h = k_cache.shape[1], k_cache.shape[2]
    if kv_h != h:
        # GQA: expand the cache to full heads for the kernel (the
        # per-kv-head block formulation violates the (8,128) tiling rule
        # for small groups); the expansion costs grp x cache traffic,
        # still a net win over materializing [h, max_len] scores
        k_cache = _repeat_kv(k_cache, h // kv_h)
        v_cache = _repeat_kv(v_cache, h // kv_h)
    nk = max_len // block_k
    scr_rows = max(h, 8)   # TPU sublane tile
    # q enters as [b, h, 1, d]: the kernel needs the head dim leading
    # for Mosaic's batch-dim-0 dot rule (the [h, d] -> [kv, grp, d]
    # reshape of the head dim is an unsupported shape cast in-kernel)
    q_t = q.transpose(0, 2, 1, 3)

    kernel = functools.partial(_decode_kernel, scale=scale, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(b, nk),
        in_specs=[
            pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, h, 1, block_k), lambda ib, j: (ib, 0, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_t, k_cache, v_cache, bias)
    return out.transpose(0, 2, 1, 3)                      # [b, 1, h, d]


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, l, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None], (b, l, h, n_rep, d)) \
        .reshape(b, l, h * n_rep, d)


def _inside_shard_map(mesh):
    """True when tracing INSIDE a ``shard_map`` body over ``mesh``: the
    mesh axis names are bound as manual axes there, so probing any of
    them succeeds.  The per-shard context must never re-trigger the
    multi-chip dispatch decision — inside the body each device already
    holds exactly its shard, and the kernel runs on local arrays."""
    for a in mesh.axis_names:
        try:
            jax.lax.axis_size(a)
            return True
        except Exception:       # NameError: axis not bound -> outside
            continue
    return False


def _multichip_mesh():
    """True when the trace-time serving mesh spans more than one device
    on the ``model``/``data`` axes — AND we are not already inside a
    ``shard_map`` body (the per-shard context sees only local arrays;
    re-triggering the mesh bypass there would route every shard to the
    gather reference and defeat the dispatch).

    GSPMD cannot partition a ``pallas_call``, so on a multi-device mesh
    the paged decode runs the kernel through the ``shard_map`` dispatch
    in :func:`paged_decode_attention` (each device runs the kernel over
    its kv-head/slot shard); the dense-cache :func:`decode_attention`
    still falls back to the jnp reference, which shards cleanly under
    GSPMD.  ``force_kernel`` still overrides (single-device parity
    tests)."""
    from deepspeed_tpu import comm as dist
    mesh = dist.get_mesh()
    if mesh is None:
        return False
    if not any(int(mesh.shape.get(a, 1)) > 1 for a in ("model", "data")):
        return False
    return not _inside_shard_map(mesh)


def _paged_decode_kernel_quant(pt_ref, len_ref, q_ref, k_ref, v_ref,
                               ks_ref, vs_ref, o_ref, m_scr, l_scr,
                               acc_scr, *, scale, page_size, np_):
    """Quantized-pool variant of ``_paged_decode_kernel``: the K/V page
    block arrives int8/fp8 and its per-row scale block ([1, page_size,
    h, 1] — the parallel scale pool, fetched through the SAME
    scalar-prefetched page-table index map, so a page and its scales
    are one unit) dequantizes in VMEM right before the dot — the
    fused-dequant property that makes quantized decode a bandwidth win
    rather than a copy: only quantized bytes ever stream from HBM."""
    si = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    pos = len_ref[si]

    @pl.when(ki * page_size <= pos)
    def _compute():
        q = q_ref[0]                                      # [h, 1, d]
        k = (k_ref[0].astype(jnp.float32) *
             ks_ref[0].astype(jnp.float32)).astype(q.dtype)
        v = (v_ref[0].astype(jnp.float32) *
             vs_ref[0].astype(jnp.float32)).astype(q.dtype)
        k = k.transpose(1, 0, 2)                          # [h, ps, d]
        v = v.transpose(1, 0, 2)                          # [h, ps, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [h, 1, ps]
        k_pos = ki * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        s = jnp.maximum(s, NEG_INF)

        m_prev = m_scr[:h, :1]
        l_prev = l_scr[:h, :1]
        m_cur = jnp.max(s, axis=2)
        m_new = jnp.maximum(m_prev, m_cur)
        row_live = m_new > NEG_INF / 2
        alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]),
                      0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [h, 1, d]
        acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
        m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
        l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == np_ - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, page_size, np_):
    """Paged variant of ``_decode_kernel``: one grid step is ALL heads of
    one slot against ONE cache page, fetched through the prefetched page
    table (the BlockSpec index_map picks the page id, so K/V stream
    page-by-page from the shared pool — the gathered [slots, max_len]
    copy of the jnp fallback never exists). The validity mask is computed
    in-kernel from the prefetched per-slot position: key position
    ``page * page_size + offset`` is live iff <= the slot's current
    position."""
    si = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    pos = len_ref[si]

    # skip pages entirely past the slot's live prefix (their state
    # contribution is exactly zero); the page the cursor sits in still
    # runs with the in-kernel mask
    @pl.when(ki * page_size <= pos)
    def _compute():
        q = q_ref[0]                                      # [h, 1, d]
        k = k_ref[0].transpose(1, 0, 2)                   # [h, ps, d]
        v = v_ref[0].transpose(1, 0, 2)                   # [h, ps, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [h, 1, ps]
        k_pos = ki * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        s = jnp.maximum(s, NEG_INF)

        m_prev = m_scr[:h, :1]
        l_prev = l_scr[:h, :1]
        m_cur = jnp.max(s, axis=2)
        m_new = jnp.maximum(m_prev, m_cur)
        row_live = m_new > NEG_INF / 2
        alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [h, 1, d]
        acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
        m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
        l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == np_ - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _paged_decode_kernel_gqa(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                             scale, page_size, np_, quantized):
    """GQA-native paged decode: one grid step is ONE kv head's GROUP of
    query heads against one page, so the grid is (slots, kv_heads,
    pages) and the K/V BlockSpec picks a single kv head — the pool is
    never expanded to full heads (the ``_repeat_kv`` copy the original
    auto path paid group_factor x pool bytes for).  ``q`` arrives
    pre-reshaped [slots, kv_heads, group, d] (query head kv*group + g
    belongs to kv head kv — the same contiguous grouping
    ``_repeat_kv`` spells out), so the per-step dot is a plain
    [group, d] x [page_size, d]^T matmul.  ``quantized`` appends the
    per-row scale refs ([1, page_size, 1, 1] blocks riding the SAME
    prefetched page-table index map) and dequantizes in VMEM before
    the dot.  Tiling note: blocks expose (group, d) / (page_size, d)
    as their trailing dims; a sub-8 ``group`` relies on Mosaic padding
    the sublane tile — interpret mode (CI) is exact either way, and
    the real-TPU bench run is where the tile economics get measured."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    si = pl.program_id(0)
    ki = pl.program_id(2)

    # pages is the innermost grid dim: ki resets to 0 whenever the
    # (slot, kv head) pair advances, so this init starts a fresh
    # online-softmax accumulation per pair
    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    g = q_ref.shape[2]
    pos = len_ref[si]

    @pl.when(ki * page_size <= pos)
    def _compute():
        q = q_ref[0, 0]                                   # [group, d]
        k = k_ref[0, :, 0, :]                             # [ps, d]
        v = v_ref[0, :, 0, :]                             # [ps, d]
        if quantized:
            k = (k.astype(jnp.float32) *
                 ks_ref[0, :, 0, :].astype(jnp.float32)).astype(q.dtype)
            v = (v.astype(jnp.float32) *
                 vs_ref[0, :, 0, :].astype(jnp.float32)).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [group, ps]
        k_pos = ki * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        s = jnp.maximum(s, NEG_INF)

        m_prev = m_scr[:g, :1]
        l_prev = l_scr[:g, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        row_live = m_new > NEG_INF / 2
        alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(row_live, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [group, d]
        acc_scr[:g] = acc_scr[:g] * alpha + pv
        m_scr[:g] = jnp.broadcast_to(m_new, (g, m_scr.shape[1]))
        l_scr[:g] = jnp.broadcast_to(l_new, (g, l_scr.shape[1]))

    @pl.when(ki == np_ - 1)
    def _finalize():
        l = l_scr[:g, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:g] / l).astype(o_ref.dtype)


def _paged_decode_pallas_gqa(q, k_pages, v_pages, page_table, positions, *,
                             scale, interpret, k_scale=None, v_scale=None):
    """Grouped-query paged kernel dispatch: grid (slots, kv_heads,
    pages), per-kv-head BlockSpecs — see ``_paged_decode_kernel_gqa``.
    Shapes as in :func:`_paged_decode_pallas`."""
    slots, one, h, d = q.shape
    page_size, kv_h = k_pages.shape[1], k_pages.shape[2]
    maxp = page_table.shape[1]
    group = h // kv_h
    quantized = k_scale is not None
    # [slots, 1, h, d] -> [slots, kv_h, group, d]: head kv*group + g is
    # kv head kv's g-th query head (the _repeat_kv grouping)
    q_g = q.transpose(0, 2, 1, 3).reshape(slots, kv_h, group, d)
    scr_rows = max(group, 8)   # TPU sublane tile

    page_spec = pl.BlockSpec(
        (1, page_size, 1, d),
        lambda si, hi, ki, pt, ln: (pt[si, ki], 0, hi, 0))
    q_spec = pl.BlockSpec((1, 1, group, d),
                          lambda si, hi, ki, pt, ln: (si, hi, 0, 0))
    in_specs = [q_spec, page_spec, page_spec]
    operands = [q_g, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, page_size, 1, 1),
            lambda si, hi, ki, pt, ln: (pt[si, ki], 0, hi, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    kernel = functools.partial(_paged_decode_kernel_gqa, scale=scale,
                               page_size=page_size, np_=maxp,
                               quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, kv_h, maxp),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, kv_h, group, d), q.dtype),
        interpret=interpret,
    )(page_table, positions, *operands)
    return out.reshape(slots, h, d)[:, None]              # [slots, 1, h, d]


def _paged_decode_pallas(q, k_pages, v_pages, page_table, positions, *,
                         scale, interpret, k_scale=None, v_scale=None):
    slots, one, h, d = q.shape
    page_size = k_pages.shape[1]
    maxp = page_table.shape[1]
    kv_h = k_pages.shape[2]
    quantized = k_scale is not None
    if kv_h != h:
        # grouped (GQA) pools get the per-kv-head BlockSpec kernel: the
        # q-head group rides in per kv head and the pool streams its
        # native grouped layout (no _repeat_kv expansion copying
        # group x pool bytes per step)
        return _paged_decode_pallas_gqa(
            q, k_pages, v_pages, page_table, positions, scale=scale,
            interpret=interpret, k_scale=k_scale, v_scale=v_scale)
    scr_rows = max(h, 8)
    q_t = q.transpose(0, 2, 1, 3)                         # [slots, h, 1, d]

    page_spec = pl.BlockSpec((1, page_size, h, d),
                             lambda si, ki, pt, ln: (pt[si, ki], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, 1, d), lambda si, ki, pt, ln: (si, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q_t, k_pages, v_pages]
    if quantized:
        # the scale pools ride the SAME prefetched page-table index map
        # as their payload: one grid step fetches a page and its scales
        # as a unit, and the dequant happens in VMEM inside the kernel
        scale_spec = pl.BlockSpec(
            (1, page_size, h, 1),
            lambda si, ki, pt, ln: (pt[si, ki], 0, 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
        kernel = functools.partial(_paged_decode_kernel_quant,
                                   scale=scale, page_size=page_size,
                                   np_=maxp)
    else:
        kernel = functools.partial(_paged_decode_kernel, scale=scale,
                                   page_size=page_size, np_=maxp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, 1, d),
                               lambda si, ki, pt, ln: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, 1, d), q.dtype),
        interpret=interpret,
    )(page_table, positions, *operands)
    return out.transpose(0, 2, 1, 3)                      # [slots, 1, h, d]


_KERNEL_MODE = None       # None -> "auto"; see kernel_mode_scope

PAGED_KERNEL_MODES = ("auto", "force", "reference")


class kernel_mode_scope:
    """Trace-time channel for the paged-kernel dispatch policy: the
    engine wraps every serving trace in
    ``kernel_mode_scope(engine.paged_kernel_mode)`` so
    :func:`paged_decode_attention` resolves kernel-vs-reference with
    the engine's CONFIGURED mode ("auto" | "force" | "reference").
    The mode is an engine-lifetime static — it picks the traced branch,
    so flipping it after the serving fns compiled would not retrace
    (same contract as the mesh/rule-table scopes)."""

    def __init__(self, mode):
        self.mode = mode
        self._saved = None

    def __enter__(self):
        global _KERNEL_MODE
        self._saved = _KERNEL_MODE
        _KERNEL_MODE = self.mode
        return self.mode

    def __exit__(self, *exc):
        global _KERNEL_MODE
        _KERNEL_MODE = self._saved
        return False


def paged_kernel_decision(*, num_heads, num_kv_heads, page_size,
                          mesh=None, mode="auto", has_bias=False,
                          backend=None):
    """THE paged-attention kernel-eligibility decision, as data: returns
    ``{"path": "kernel"|"reference", "dispatch": "shard_map"|"direct"|
    None, "reason": str}``.  :func:`paged_decode_attention` makes this
    exact decision at trace time; the engine surfaces it through
    ``serving_mesh_info()``/``health()`` (one-shot logged at pool
    construction) so an accidental reference-path fallback is VISIBLE
    instead of silent — the decision depends only on static config
    (model head counts, page size, mesh, backend, mode), never on
    per-step data, so the two views cannot disagree.

    ``dispatch`` says HOW the kernel runs: "direct" is a plain
    ``pallas_call`` (single device), "shard_map" wraps it per-shard
    over the mesh (each device runs the kernel on its kv-head/slot
    shard — GSPMD cannot partition a ``pallas_call``, so multi-chip
    kernels only exist through this dispatch)."""
    if mode not in PAGED_KERNEL_MODES:
        raise ValueError(f"unknown paged-kernel mode {mode!r}; pick one "
                         f"of {PAGED_KERNEL_MODES}")
    multi = False
    if mesh is not None:
        multi = any(int(mesh.shape.get(a, 1)) > 1
                    for a in ("model", "data"))
    disp = "shard_map" if multi else "direct"

    def ref(reason):
        return {"path": "reference", "dispatch": None, "reason": reason}

    if pltpu is None:
        return ref("this jax build has no Pallas TPU backend")
    if has_bias:
        return ref("additive bias (ALiBi) rides the gather reference "
                   "(the paged kernel computes only the positional "
                   "mask in-kernel)")
    if num_kv_heads and num_heads % num_kv_heads != 0:
        return ref(f"num_heads={num_heads} is not a multiple of "
                   f"num_kv_heads={num_kv_heads}")
    if mode == "reference":
        return ref("paged_kernel='reference' pins the gather fallback")
    if mode == "force":
        return {"path": "kernel", "dispatch": disp,
                "reason": "paged_kernel='force' pins the kernel "
                          "(interpret mode off-TPU)"}
    backend = jax.default_backend() if backend is None else backend
    if backend != "tpu":
        return ref(f"off-TPU backend {backend!r}: interpret-mode Pallas "
                   "is slower than the jnp reference "
                   "(paged_kernel='force' overrides for parity runs)")
    if page_size is None:
        return ref("page size unknown until the paged pool is built")
    if page_size % 128 != 0:
        # `blocker` is the STRUCTURED spelling of this gate: the
        # engine's constructor-time warning keys on it, never on the
        # human-readable reason wording
        out = ref(f"page_size={page_size} is not a multiple of 128 "
                  "(the TPU lane tile): the paged Pallas kernel "
                  "cannot tile its pages — pick page_size 128/256 to "
                  "enable the kernel path")
        out["blocker"] = "page_size"
        return out
    return {"path": "kernel", "dispatch": disp,
            "reason": "TPU backend, 128-aligned pages"
                      + (" — shard_mapped over the mesh" if multi
                         else "")}


def _shard_map_axes(mesh, slots, h, kv_h):
    """Resolve which mesh axes the shard_map dispatch partitions over,
    from the ACTIVE serving rule table (serving/sharding.py
    ``config_scope`` — the same trace-time channel
    ``constrain_kv_pages`` reads, so the per-shard split always agrees
    with the pinned pool/carry shardings).  An axis that cannot divide
    its dim degrades to replicated for that dim — exactly mirroring
    ``ServingShardingConfig.resolve``'s slot-family degrade."""
    from deepspeed_tpu.serving.sharding import active_rules
    rules = active_rules()
    kv_ax = rules.get("kv_heads")
    slot_ax = rules.get("slots")
    msize = int(mesh.shape.get(kv_ax, 1)) if kv_ax else 1
    dsize = int(mesh.shape.get(slot_ax, 1)) if slot_ax else 1
    head_ax = kv_ax if (msize > 1 and kv_h % msize == 0 and
                        h % msize == 0) else None
    s_ax = slot_ax if (dsize > 1 and slots % dsize == 0) else None
    return head_ax, s_ax


def _paged_decode_shard_map(q, k_pages, v_pages, page_table, positions,
                            *, scale, interpret, mesh, k_scale=None,
                            v_scale=None):
    """Run the paged kernel per-shard over the serving mesh: kv pools
    enter sharded [pages, ps, KV_H/model, dim] (each device holds its
    kv-head slice of EVERY page — page ids are global, the host-side
    page table needs no translation), q/page_table/positions shard
    their slot dim over ``data``, and each shard runs the ordinary
    kernel on its local arrays — so per-shard BlockSpecs need no new
    indexing, and GQA groups stay intact (the q-head group belonging
    to the local kv shard rides in; a sharded MHA model sees grouped
    heads the same way).  Inside the body ``_multichip_mesh`` reports
    False (the axis names are bound), so nothing re-triggers the mesh
    bypass."""
    from jax.sharding import PartitionSpec as P
    slots, _, h, d = q.shape
    kv_h = k_pages.shape[2]
    head_ax, slot_ax = _shard_map_axes(mesh, slots, h, kv_h)
    q_spec = P(slot_ax, None, head_ax, None)
    pool_spec = P(None, None, head_ax, None)
    in_specs = [q_spec, pool_spec, pool_spec, P(slot_ax, None),
                P(slot_ax)]
    args = [q, k_pages, v_pages, page_table, positions]
    if k_scale is not None:
        in_specs += [pool_spec, pool_spec]
        args += [k_scale, v_scale]

    def body(q_, kp_, vp_, pt_, pos_, *scales):
        ks, vs = scales if scales else (None, None)
        return _paged_decode_pallas(q_, kp_, vp_, pt_, pos_, scale=scale,
                                    interpret=interpret, k_scale=ks,
                                    v_scale=vs)

    return jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=q_spec, check_vma=False)(*args)


def gather_pages(pages, page_table):
    """[num_pages, page_size, kv_h, d] gathered through [slots, maxp] ->
    contiguous per-slot buffers [slots, maxp*page_size, kv_h, d].
    Unallocated table entries must point at a valid page id (0); the
    caller's validity mask covers those positions."""
    g = pages[page_table]
    s, mp, ps, h, d = g.shape
    return g.reshape(s, mp * ps, h, d)


def paged_decode_attention(q, k_pages, v_pages, page_table, positions, *,
                           scale=None, bias=None, interpret=None,
                           force_kernel=False, k_scale=None,
                           v_scale=None):
    """Single-token attention of ``q`` [slots, 1, heads, d] over a PAGED
    cache: a shared pool ``k_pages``/``v_pages`` [num_pages, page_size,
    kv_heads, d] indexed through ``page_table`` [slots, max_pages] with
    per-slot query ``positions`` [slots] (key positions <= position are
    live — the current token's k/v must already be written).

    ``k_scale``/``v_scale`` (optional, [num_pages, page_size, kv_heads,
    1] f32) mark a QUANTIZED pool (int8/fp8 payload + per-row scales,
    ops/quant/kv.py): the Pallas path fetches each page's scale block
    through the same scalar-prefetched page-table index map and
    dequantizes in VMEM right before the dot (only quantized bytes
    stream from HBM — the bandwidth win), while the fallback gathers
    payload + scales and dequantizes the contiguous buffers (the jnp
    reference for CPU/mesh parity).

    The Pallas path streams K/V page-by-page via scalar-prefetched table
    lookups (true PagedAttention: no per-slot contiguous copy); GQA
    pools run it with per-kv-head BlockSpecs (the q-head group rides in
    per kv head — the pool is never expanded), and on a multi-device
    mesh it runs per-shard under ``shard_map`` (kv heads over
    ``model``, slots over ``data``; see ``_paged_decode_shard_map``).
    The fallback gathers pages into contiguous buffers and reuses
    :func:`decode_attention` — correct everywhere (it is the jnp
    correctness oracle, and what GSPMD partitions when the kernel is
    ineligible), but it materializes [slots, max_pages*page_size] K/V
    transiently.  :func:`paged_kernel_decision` is the one
    kernel-vs-reference rule; the engine exports it through
    ``serving_mesh_info()``/``health()``.

    ``bias`` (optional, broadcastable to [slots, heads, 1, max_len])
    carries extra additive terms (ALiBi); when present the fallback path
    runs (the paged kernel computes only the positional mask in-kernel).

    Both paths are ``lax.scan``-compatible: every branch decision here
    is made on static python values, and ``positions``/``page_table``
    may be traced carries — the fused multi-step serving decode
    (``InferenceEngine.decode_multi``) scans this step with on-device
    token feedback, so nothing in here may force a host sync or a
    per-iteration retrace.
    """
    slots, l, h, d = q.shape
    page_size = k_pages.shape[1]
    kv_h = k_pages.shape[2]
    max_len = page_table.shape[1] * page_size
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    positions = positions.astype(jnp.int32)

    # Kernel-vs-reference dispatch (all static, scan-safe): the
    # decision is paged_kernel_decision's — the same function the
    # engine surfaces through serving_mesh_info()/health(), so the
    # active path is always visible to operators.  GQA pools run the
    # per-kv-head BlockSpec kernel (grid (slots, kv_heads, pages) — no
    # pool expansion); on a multi-device mesh the kernel runs through
    # the shard_map dispatch, each device over its kv-head/slot shard
    # (GSPMD cannot partition a pallas_call, so this dispatch is the
    # ONLY multi-chip kernel path — the jnp reference below remains
    # the GSPMD-partitionable correctness oracle).  Inside a shard_map
    # body the mesh axes are bound, _multichip_mesh reports False, and
    # the decision resolves "direct" — the per-shard kernel never
    # re-triggers the bypass.
    from deepspeed_tpu import comm as dist
    mesh = dist.get_mesh()
    if mesh is not None and _inside_shard_map(mesh):
        mesh = None
    mode = "force" if force_kernel else (_KERNEL_MODE or "auto")
    decision = paged_kernel_decision(
        num_heads=h, num_kv_heads=kv_h, page_size=page_size, mesh=mesh,
        mode=mode, has_bias=bias is not None)
    if l == 1 and decision["path"] == "kernel":
        if decision["dispatch"] == "shard_map":
            return _paged_decode_shard_map(
                q, k_pages, v_pages, page_table.astype(jnp.int32),
                positions, scale=scale, interpret=interpret, mesh=mesh,
                k_scale=k_scale, v_scale=v_scale)
        return _paged_decode_pallas(q, k_pages, v_pages,
                                    page_table.astype(jnp.int32), positions,
                                    scale=scale, interpret=interpret,
                                    k_scale=k_scale, v_scale=v_scale)

    k_full = gather_pages(k_pages, page_table)
    v_full = gather_pages(v_pages, page_table)
    if k_scale is not None:
        from deepspeed_tpu.ops.quant.kv import dequantize_kv_rows
        k_full = dequantize_kv_rows(k_full, gather_pages(k_scale,
                                                         page_table),
                                    q.dtype)
        v_full = dequantize_kv_rows(v_full, gather_pages(v_scale,
                                                         page_table),
                                    q.dtype)
    k_pos = jnp.arange(max_len)
    mask = k_pos[None, None, None, :] <= positions[:, None, None, None]
    full_bias = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
    if bias is not None:
        full_bias = full_bias + bias.astype(jnp.float32)
    return decode_attention(q, k_full, v_full, bias=full_bias, scale=scale,
                            interpret=interpret)


def decode_attention(q, k_cache, v_cache, *, bias, scale=None,
                     interpret=None, block_k=None, force_kernel=False):
    """Attention of `q` [b, l, heads, d] over a cache buffer
    [b, max_len, kv_heads, d] with additive `bias` (broadcastable to
    [b, heads, l, max_len]) carrying the validity mask.

    Single-token decode (l == 1) runs the Pallas kernel on TPU;
    multi-token (prefill into a cache) falls back to the jnp oracle. GQA
    caches (kv_heads < heads) are consumed directly by the kernel.

    Off-TPU the kernel would run in interpret mode — a grid of emulated
    Mosaic steps that is both slower at runtime than the plain jnp
    reference and much heavier to trace, which matters now that the
    serving decode loops this step under ``lax.scan``
    (``InferenceEngine.decode_multi`` traces the body once per horizon
    bucket). Interpret-mode decode therefore routes to the reference
    path unless ``force_kernel`` pins the kernel (parity tests).
    """
    from deepspeed_tpu.ops.attention.reference import mha_reference

    b, l, h, d = q.shape
    kv_h = k_cache.shape[2]
    max_len = k_cache.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if l == 1 and h % kv_h == 0 and max_len % (block_k or 128) == 0 and \
            (force_kernel or not (interpret or _multichip_mesh())):
        block_k = block_k or _pick_block(max_len)
        bias_full = jnp.broadcast_to(
            bias.astype(jnp.float32), (b, h, 1, max_len))
        return _decode_pallas(q, k_cache, v_cache, bias_full, scale=scale,
                              block_k=block_k, interpret=interpret)

    k_full = _repeat_kv(k_cache, h // kv_h)
    v_full = _repeat_kv(v_cache, h // kv_h)
    return mha_reference(q, k_full, v_full, causal=False, bias=bias,
                         scale=scale)
