"""Pallas TPU KV-cache decode attention (the `softmax_context` kernel).

TPU-native replacement for the reference's inference attention kernel
(csrc/transformer/inference/csrc/pt_binding.cpp `softmax_context`,
`inference_context.h` KV workspace): single-token queries attend over a
device-resident cache buffer without materializing [heads, max_len]
score tensors in HBM, with additive bias (position mask, ALiBi).

Design:
  * caches stay in their storage layout [batch, max_len, kv_heads, dim] —
    BlockSpecs index directly into it, no transpose copies per token.
  * grid = (batch, kv_heads, k_blocks); the k axis is innermost so the
    online-softmax state lives in VMEM scratch across grid steps
    (same scheme as ops/attention/flash.py).
  * GQA is native: each kv head's grid step loads its whole group of
    query heads ([group, dim] block), so grouped caches are never
    expanded to num_heads (the `_repeat_kv` copy disappears).
  * bias [batch, heads, 1, max_len] carries the validity mask (slots past
    the write index) and any ALiBi term; fp32 statistics throughout.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import NEG_INF, _pick_block


def _decode_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, nk):
    """One grid step: ALL heads against one kv block. Blocks span the
    full head dims (equal-to-array, so any head count satisfies the TPU
    (8,128) tiling rule), and the per-head products use dot_general
    batch dims directly on the cache's storage layout — Mosaic rejects
    both the reshape ([h,d]->[kv,grp,d], "unsupported shape cast") and
    per-head sub-8 blocks, so no reshapes or transposes appear here."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    q = q_ref[0]                                          # [h, 1, d]
    k = k_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    v = v_ref[0].transpose(1, 0, 2)                       # [h, bk, d]
    # leading-batch dot over heads (Mosaic supports batch dims only at
    # position 0 on both sides — hence q pre-shaped [h, 1, d] outside)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale       # [h, 1, bk]
    s = s + bias_ref[0]                                   # [h, 1, bk]
    s = jnp.maximum(s, NEG_INF)  # keep masked slots finite (see flash.py)

    m_prev = m_scr[:h, :1]
    l_prev = l_scr[:h, :1]
    m_cur = jnp.max(s, axis=2)                            # [h, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    row_live = m_new > NEG_INF / 2
    alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
    p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=2)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)               # [h, 1, d]
    acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
    m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
    l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _decode_pallas(q, k_cache, v_cache, bias, *, scale, block_k, interpret):
    b, one, h, d = q.shape
    max_len, kv_h = k_cache.shape[1], k_cache.shape[2]
    if kv_h != h:
        # GQA: expand the cache to full heads for the kernel (the
        # per-kv-head block formulation violates the (8,128) tiling rule
        # for small groups); the expansion costs grp x cache traffic,
        # still a net win over materializing [h, max_len] scores
        k_cache = _repeat_kv(k_cache, h // kv_h)
        v_cache = _repeat_kv(v_cache, h // kv_h)
    nk = max_len // block_k
    scr_rows = max(h, 8)   # TPU sublane tile
    # q enters as [b, h, 1, d]: the kernel needs the head dim leading
    # for Mosaic's batch-dim-0 dot rule (the [h, d] -> [kv, grp, d]
    # reshape of the head dim is an unsupported shape cast in-kernel)
    q_t = q.transpose(0, 2, 1, 3)

    kernel = functools.partial(_decode_kernel, scale=scale, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(b, nk),
        in_specs=[
            pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, block_k, h, d), lambda ib, j: (ib, j, 0, 0)),
            pl.BlockSpec((1, h, 1, block_k), lambda ib, j: (ib, 0, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, h, 1, d), lambda ib, j: (ib, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_t, k_cache, v_cache, bias)
    return out.transpose(0, 2, 1, 3)                      # [b, 1, h, d]


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, l, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None], (b, l, h, n_rep, d)) \
        .reshape(b, l, h * n_rep, d)


def _multichip_mesh():
    """True when the trace-time serving mesh spans more than one device
    on the ``model``/``data`` axes.  GSPMD cannot partition a
    ``pallas_call``, so the decode kernels must not see mesh-sharded
    operands: the jnp fallback shards cleanly under GSPMD (slots over
    `data`, kv heads over `model`) and is what multi-chip serving
    routes through — a shard_mapped per-shard paged kernel is the
    follow-up, not a silent wrong answer.  ``force_kernel`` still
    overrides (single-device parity tests)."""
    from deepspeed_tpu import comm as dist
    mesh = dist.get_mesh()
    if mesh is None:
        return False
    return any(int(mesh.shape.get(a, 1)) > 1 for a in ("model", "data"))


def _paged_decode_kernel_quant(pt_ref, len_ref, q_ref, k_ref, v_ref,
                               ks_ref, vs_ref, o_ref, m_scr, l_scr,
                               acc_scr, *, scale, page_size, np_):
    """Quantized-pool variant of ``_paged_decode_kernel``: the K/V page
    block arrives int8/fp8 and its per-row scale block ([1, page_size,
    h, 1] — the parallel scale pool, fetched through the SAME
    scalar-prefetched page-table index map, so a page and its scales
    are one unit) dequantizes in VMEM right before the dot — the
    fused-dequant property that makes quantized decode a bandwidth win
    rather than a copy: only quantized bytes ever stream from HBM."""
    si = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    pos = len_ref[si]

    @pl.when(ki * page_size <= pos)
    def _compute():
        q = q_ref[0]                                      # [h, 1, d]
        k = (k_ref[0].astype(jnp.float32) *
             ks_ref[0].astype(jnp.float32)).astype(q.dtype)
        v = (v_ref[0].astype(jnp.float32) *
             vs_ref[0].astype(jnp.float32)).astype(q.dtype)
        k = k.transpose(1, 0, 2)                          # [h, ps, d]
        v = v.transpose(1, 0, 2)                          # [h, ps, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [h, 1, ps]
        k_pos = ki * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        s = jnp.maximum(s, NEG_INF)

        m_prev = m_scr[:h, :1]
        l_prev = l_scr[:h, :1]
        m_cur = jnp.max(s, axis=2)
        m_new = jnp.maximum(m_prev, m_cur)
        row_live = m_new > NEG_INF / 2
        alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]),
                      0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [h, 1, d]
        acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
        m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
        l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == np_ - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, page_size, np_):
    """Paged variant of ``_decode_kernel``: one grid step is ALL heads of
    one slot against ONE cache page, fetched through the prefetched page
    table (the BlockSpec index_map picks the page id, so K/V stream
    page-by-page from the shared pool — the gathered [slots, max_len]
    copy of the jnp fallback never exists). The validity mask is computed
    in-kernel from the prefetched per-slot position: key position
    ``page * page_size + offset`` is live iff <= the slot's current
    position."""
    si = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    h = q_ref.shape[1]
    pos = len_ref[si]

    # skip pages entirely past the slot's live prefix (their state
    # contribution is exactly zero); the page the cursor sits in still
    # runs with the in-kernel mask
    @pl.when(ki * page_size <= pos)
    def _compute():
        q = q_ref[0]                                      # [h, 1, d]
        k = k_ref[0].transpose(1, 0, 2)                   # [h, ps, d]
        v = v_ref[0].transpose(1, 0, 2)                   # [h, ps, d]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [h, 1, ps]
        k_pos = ki * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        s = jnp.maximum(s, NEG_INF)

        m_prev = m_scr[:h, :1]
        l_prev = l_scr[:h, :1]
        m_cur = jnp.max(s, axis=2)
        m_new = jnp.maximum(m_prev, m_cur)
        row_live = m_new > NEG_INF / 2
        alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
        p = jnp.where(row_live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=2)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [h, 1, d]
        acc_scr[:h] = acc_scr[:h] * alpha + pv[:, 0, :]
        m_scr[:h] = jnp.broadcast_to(m_new, (h, m_scr.shape[1]))
        l_scr[:h] = jnp.broadcast_to(l_new, (h, l_scr.shape[1]))

    @pl.when(ki == np_ - 1)
    def _finalize():
        l = l_scr[:h, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = ((acc_scr[:h] / l)[:, None, :]).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, positions, *,
                         scale, interpret, k_scale=None, v_scale=None):
    slots, one, h, d = q.shape
    page_size = k_pages.shape[1]
    maxp = page_table.shape[1]
    kv_h = k_pages.shape[2]
    quantized = k_scale is not None
    if kv_h != h:
        k_pages = _repeat_kv(k_pages, h // kv_h)
        v_pages = _repeat_kv(v_pages, h // kv_h)
        if quantized:
            k_scale = _repeat_kv(k_scale, h // kv_h)
            v_scale = _repeat_kv(v_scale, h // kv_h)
    scr_rows = max(h, 8)
    q_t = q.transpose(0, 2, 1, 3)                         # [slots, h, 1, d]

    page_spec = pl.BlockSpec((1, page_size, h, d),
                             lambda si, ki, pt, ln: (pt[si, ki], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h, 1, d), lambda si, ki, pt, ln: (si, 0, 0, 0)),
        page_spec, page_spec,
    ]
    operands = [q_t, k_pages, v_pages]
    if quantized:
        # the scale pools ride the SAME prefetched page-table index map
        # as their payload: one grid step fetches a page and its scales
        # as a unit, and the dequant happens in VMEM inside the kernel
        scale_spec = pl.BlockSpec(
            (1, page_size, h, 1),
            lambda si, ki, pt, ln: (pt[si, ki], 0, 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
        kernel = functools.partial(_paged_decode_kernel_quant,
                                   scale=scale, page_size=page_size,
                                   np_=maxp)
    else:
        kernel = functools.partial(_paged_decode_kernel, scale=scale,
                                   page_size=page_size, np_=maxp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, maxp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, 1, d),
                               lambda si, ki, pt, ln: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, 128), jnp.float32),
            pltpu.VMEM((scr_rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, 1, d), q.dtype),
        interpret=interpret,
    )(page_table, positions, *operands)
    return out.transpose(0, 2, 1, 3)                      # [slots, 1, h, d]


def gather_pages(pages, page_table):
    """[num_pages, page_size, kv_h, d] gathered through [slots, maxp] ->
    contiguous per-slot buffers [slots, maxp*page_size, kv_h, d].
    Unallocated table entries must point at a valid page id (0); the
    caller's validity mask covers those positions."""
    g = pages[page_table]
    s, mp, ps, h, d = g.shape
    return g.reshape(s, mp * ps, h, d)


def paged_decode_attention(q, k_pages, v_pages, page_table, positions, *,
                           scale=None, bias=None, interpret=None,
                           force_kernel=False, k_scale=None,
                           v_scale=None):
    """Single-token attention of ``q`` [slots, 1, heads, d] over a PAGED
    cache: a shared pool ``k_pages``/``v_pages`` [num_pages, page_size,
    kv_heads, d] indexed through ``page_table`` [slots, max_pages] with
    per-slot query ``positions`` [slots] (key positions <= position are
    live — the current token's k/v must already be written).

    ``k_scale``/``v_scale`` (optional, [num_pages, page_size, kv_heads,
    1] f32) mark a QUANTIZED pool (int8/fp8 payload + per-row scales,
    ops/quant/kv.py): the Pallas path fetches each page's scale block
    through the same scalar-prefetched page-table index map and
    dequantizes in VMEM right before the dot (only quantized bytes
    stream from HBM — the bandwidth win), while the fallback gathers
    payload + scales and dequantizes the contiguous buffers (the jnp
    reference for CPU/mesh parity).

    The Pallas path streams K/V page-by-page via scalar-prefetched table
    lookups (true PagedAttention: no per-slot contiguous copy). The
    fallback gathers pages into contiguous buffers and reuses
    :func:`decode_attention` — correct everywhere, but it materializes
    [slots, max_pages*page_size] K/V transiently.

    ``bias`` (optional, broadcastable to [slots, heads, 1, max_len])
    carries extra additive terms (ALiBi); when present the fallback path
    runs (the paged kernel computes only the positional mask in-kernel).

    Both paths are ``lax.scan``-compatible: every branch decision here
    is made on static python values, and ``positions``/``page_table``
    may be traced carries — the fused multi-step serving decode
    (``InferenceEngine.decode_multi``) scans this step with on-device
    token feedback, so nothing in here may force a host sync or a
    per-iteration retrace.
    """
    slots, l, h, d = q.shape
    page_size = k_pages.shape[1]
    kv_h = k_pages.shape[2]
    max_len = page_table.shape[1] * page_size
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    positions = positions.astype(jnp.int32)

    # GQA pools stay on the gather fallback in auto mode: expanding the
    # WHOLE pool to full heads (the contiguous kernel's _repeat_kv trick)
    # would copy group_factor x pool bytes per step — more traffic than
    # the per-slot gather it is meant to avoid. A true GQA paged kernel
    # needs per-kv-head BlockSpec mapping (future work); force_kernel
    # still exercises the expansion path for parity tests.
    use_kernel = (l == 1 and bias is None and pltpu is not None and
                  h % kv_h == 0 and
                  (force_kernel or (kv_h == h and page_size % 128 == 0 and
                                    jax.default_backend() == "tpu" and
                                    not _multichip_mesh())))
    if use_kernel:
        return _paged_decode_pallas(q, k_pages, v_pages,
                                    page_table.astype(jnp.int32), positions,
                                    scale=scale, interpret=interpret,
                                    k_scale=k_scale, v_scale=v_scale)

    k_full = gather_pages(k_pages, page_table)
    v_full = gather_pages(v_pages, page_table)
    if k_scale is not None:
        from deepspeed_tpu.ops.quant.kv import dequantize_kv_rows
        k_full = dequantize_kv_rows(k_full, gather_pages(k_scale,
                                                         page_table),
                                    q.dtype)
        v_full = dequantize_kv_rows(v_full, gather_pages(v_scale,
                                                         page_table),
                                    q.dtype)
    k_pos = jnp.arange(max_len)
    mask = k_pos[None, None, None, :] <= positions[:, None, None, None]
    full_bias = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
    if bias is not None:
        full_bias = full_bias + bias.astype(jnp.float32)
    return decode_attention(q, k_full, v_full, bias=full_bias, scale=scale,
                            interpret=interpret)


def decode_attention(q, k_cache, v_cache, *, bias, scale=None,
                     interpret=None, block_k=None, force_kernel=False):
    """Attention of `q` [b, l, heads, d] over a cache buffer
    [b, max_len, kv_heads, d] with additive `bias` (broadcastable to
    [b, heads, l, max_len]) carrying the validity mask.

    Single-token decode (l == 1) runs the Pallas kernel on TPU;
    multi-token (prefill into a cache) falls back to the jnp oracle. GQA
    caches (kv_heads < heads) are consumed directly by the kernel.

    Off-TPU the kernel would run in interpret mode — a grid of emulated
    Mosaic steps that is both slower at runtime than the plain jnp
    reference and much heavier to trace, which matters now that the
    serving decode loops this step under ``lax.scan``
    (``InferenceEngine.decode_multi`` traces the body once per horizon
    bucket). Interpret-mode decode therefore routes to the reference
    path unless ``force_kernel`` pins the kernel (parity tests).
    """
    from deepspeed_tpu.ops.attention.reference import mha_reference

    b, l, h, d = q.shape
    kv_h = k_cache.shape[2]
    max_len = k_cache.shape[1]
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if l == 1 and h % kv_h == 0 and max_len % (block_k or 128) == 0 and \
            (force_kernel or not (interpret or _multichip_mesh())):
        block_k = block_k or _pick_block(max_len)
        bias_full = jnp.broadcast_to(
            bias.astype(jnp.float32), (b, h, 1, max_len))
        return _decode_pallas(q, k_cache, v_cache, bias_full, scale=scale,
                              block_k=block_k, interpret=interpret)

    k_full = _repeat_kv(k_cache, h // kv_h)
    v_full = _repeat_kv(v_cache, h // kv_h)
    return mha_reference(q, k_full, v_full, causal=False, bias=bias,
                         scale=scale)
