"""Pallas TPU block-sparse flash attention (fwd + bwd).

Reference: the Triton block-sparse attention kernels
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD/DDS :196-628,
``softmax.py`` :123) driven by SparsityConfig layouts — the reference's
long-sequence story (10x longer sequences, ~6x faster; BASELINE.md).

Design — compacted look-up tables with scalar prefetch:
  * the [heads, nq, nk] block layout is compiled (at trace time, on host)
    into a LUT of active column blocks per query row: ``lut[h, qi, t]``
    and ``count[h, qi]``. The grid is ``(b*h, nq, max_active)`` — grid
    steps exist ONLY for (padded) active blocks, so both the MXU work
    AND the k/v block DMA scale with the layout density. This is the
    Pallas equivalent of the Triton kernels' ``make_lut``.
  * the LUT rides as *scalar prefetch* operands (SMEM), so BlockSpec
    index maps can read it — the pipeline knows the next block's address
    ahead of time and keeps prefetching (a data-dependent ``pl.when``
    skip would serialize Mosaic's double buffering; measured 5x slower).
  * padding steps (t >= count) re-point the DMA at the row's last active
    block (no new traffic) and skip compute.
  * causal masking stays in-kernel for diagonal blocks; callers pass
    layouts already lower-triangular for unidirectional patterns
    (flash_attention ANDs tril in).
  * backward follows flash-attention-2: dq over the same row LUT; dk/dv
    over the transposed (column -> active rows) LUT.

Measured (1 v5e chip via the dev relay, seq 8k, 4 heads, d=64, block
512, in-dispatch chained timing, 3 runs): window+global layout at ~12%
density runs ~1.35x faster than the dense layout through the same
kernel (3.4ms vs 4.5ms/iter). Both share a ~3ms fixed per-invocation
floor in this environment; subtracting it, the marginal per-block cost
scales with density as designed (~1.3us/step). The floor is an
environment/dispatch artifact of the small-batch d=64 regime, not the
kernel loop — re-profile on directly-attached chips at production
head counts.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import (NEG_INF, _bwd_p_ds,
                                               _causal_block_mask,
                                               _finalize_softmax,
                                               _online_softmax_step)


def build_luts(layout):
    """layout [H, nq, nk] int -> row LUT + transposed (column) LUT.

    Returns (lut [H, nq, A], count [H, nq], lut_t [H, nk, At],
    count_t [H, nk]); padding entries repeat the last active index so
    padded grid steps re-fetch an already-resident block."""
    layout = np.asarray(layout) != 0
    H, nq, nk = layout.shape

    def compact(mat, n_rows, n_cols):
        counts = mat.sum(axis=-1).astype(np.int32)        # [H, rows]
        A = max(int(counts.max()), 1)
        lut = np.zeros((H, n_rows, A), np.int32)
        for h in range(H):
            for r in range(n_rows):
                idx = np.nonzero(mat[h, r])[0]
                if len(idx) == 0:
                    continue
                lut[h, r, :len(idx)] = idx
                lut[h, r, len(idx):] = idx[-1]
        return lut, counts

    lut, count = compact(layout, nq, nk)
    lut_t, count_t = compact(layout.transpose(0, 2, 1), nk, nq)
    return lut, count, lut_t, count_t


def _head(i, num_heads, layout_heads):
    return jnp.mod(i, num_heads) if layout_heads > 1 else 0


# --------------------------------------------------------------------- fwd
def _fwd_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, block, causal, num_heads,
                layout_heads, n_active):
    qi = pl.program_id(1)
    t = pl.program_id(2)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(t == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    ki = lut_ref[h, qi, t]
    run = t < cnt_ref[h, qi]
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_block_mask(s, qi, ki, block, block, 0)
        _online_softmax_step(s, v, m_scr, l_scr, acc_scr)

    @pl.when(t == n_active - 1)
    def _finalize():
        _finalize_softmax(o_ref, lse_ref, m_scr, l_scr, acc_scr)


def _sparse_fwd(q3, k3, v3, lut, cnt, *, scale, block, causal, num_heads,
                interpret):
    bh, q_len, d = q3.shape
    nq = q_len // block
    A = lut.shape[2]
    H = lut.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, A),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, t, lut, cnt: (i, j, 0)),
            pl.BlockSpec((1, block, d), lambda i, j, t, lut, cnt:
                         (i, lut[_head(i, num_heads, H), j, t], 0)),
            pl.BlockSpec((1, block, d), lambda i, j, t, lut, cnt:
                         (i, lut[_head(i, num_heads, H), j, t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, t, lut, cnt: (i, j, 0)),
            pl.BlockSpec((1, block, 1), lambda i, j, t, lut, cnt: (i, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block=block, causal=causal,
        num_heads=num_heads, layout_heads=H, n_active=A)
    o, lse = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lut, cnt, q3, k3, v3)
    return o, lse


# --------------------------------------------------------------------- bwd
def _bwd_dq_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, scale, block, causal,
                   num_heads, layout_heads, n_active):
    qi = pl.program_id(1)
    t = pl.program_id(2)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(t == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    ki = lut_ref[h, qi, t]
    run = t < cnt_ref[h, qi]
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, ds = _bwd_p_ds(q, k, v, do, lse_ref[0], delta_ref[0], scale,
                          causal, qi, ki, block, block, 0)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_active - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                    block, causal, num_heads, layout_heads, n_active):
    ki = pl.program_id(1)
    t = pl.program_id(2)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    qi = lut_ref[h, ki, t]
    run = t < cnt_ref[h, ki]
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, ds = _bwd_p_ds(q, k, v, do, lse_ref[0], delta_ref[0], scale,
                          causal, qi, ki, block, block, 0)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_active - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_bwd(q3, k3, v3, o3, lse, do3, lut, cnt, lut_t, cnt_t, *, scale,
                block, causal, num_heads, interpret):
    bh, q_len, d = q3.shape
    nq = q_len // block
    A, At = lut.shape[2], lut_t.shape[2]
    H = lut.shape[0]

    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def row(i, j, t, lut, cnt):
        return (i, j, 0)

    def col_from_lut(i, j, t, lut, cnt):
        return (i, lut[_head(i, num_heads, H), j, t], 0)

    grid_dq = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, A),
        in_specs=[
            pl.BlockSpec((1, block, d), row),
            pl.BlockSpec((1, block, d), col_from_lut),
            pl.BlockSpec((1, block, d), col_from_lut),
            pl.BlockSpec((1, block, d), row),
            pl.BlockSpec((1, block, 1), row),
            pl.BlockSpec((1, block, 1), row),
        ],
        out_specs=pl.BlockSpec((1, block, d), row),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block=block,
                          causal=causal, num_heads=num_heads,
                          layout_heads=H, n_active=A),
        grid_spec=grid_dq,
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
        interpret=interpret,
    )(lut, cnt, q3, k3, v3, do3, lse, delta)

    grid_dkv = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, k3.shape[1] // block, At),
        in_specs=[
            pl.BlockSpec((1, block, d), col_from_lut),   # q rows via lut_t
            pl.BlockSpec((1, block, d), row),            # k fixed column
            pl.BlockSpec((1, block, d), row),
            pl.BlockSpec((1, block, d), col_from_lut),   # do rows
            pl.BlockSpec((1, block, 1), col_from_lut),
            pl.BlockSpec((1, block, 1), col_from_lut),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), row),
            pl.BlockSpec((1, block, d), row),
        ],
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32),
                        pltpu.VMEM((block, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block=block,
                          causal=causal, num_heads=num_heads,
                          layout_heads=H, n_active=At),
        grid_spec=grid_dkv,
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, q_len, d), v3.dtype),
        ],
        interpret=interpret,
    )(lut_t, cnt_t, q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------- entry
def make_sparse_op(layout, *, causal, scale, block, num_heads, interpret):
    """custom_vjp closing over the (static) layout's LUTs."""
    lut, cnt, lut_t, cnt_t = build_luts(layout)
    lut, cnt = jnp.asarray(lut), jnp.asarray(cnt)
    lut_t, cnt_t = jnp.asarray(lut_t), jnp.asarray(cnt_t)
    kw = dict(scale=scale, block=block, causal=causal, num_heads=num_heads,
              interpret=interpret)

    @jax.custom_vjp
    def op(q3, k3, v3):
        o, _ = _sparse_fwd(q3, k3, v3, lut, cnt, **kw)
        return o

    def fwd(q3, k3, v3):
        o, lse = _sparse_fwd(q3, k3, v3, lut, cnt, **kw)
        return o, (q3, k3, v3, o, lse)

    def bwd(res, do):
        q3, k3, v3, o, lse = res
        return _sparse_bwd(q3, k3, v3, o, lse, do, lut, cnt, lut_t, cnt_t,
                           **kw)

    op.defvjp(fwd, bwd)
    return op


_OP_CACHE = {}
_OP_CACHE_MAX = 64


def _config_key(cfg):
    def freeze(v):
        return tuple(v) if isinstance(v, (list, tuple)) else v
    return (type(cfg).__name__,) + tuple(
        (k, freeze(v)) for k, v in sorted(cfg.__dict__.items()))


def sparse_flash_attention(q, k, v, sparsity_config, *, causal=True,
                           scale=None, interpret=None):
    """Block-sparse attention on [batch, len, heads, head_dim] inputs,
    pattern from a SparsityConfig (ops/sparse_attention). Ops (and their
    host-built LUTs) are cached per (config, seq, heads, ...) so repeated
    calls/retraces skip the O(heads * blocks^2) layout compaction."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "block-sparse attention needs the Pallas TPU backend "
            "(jax.experimental.pallas.tpu); use mha_reference with "
            "layout_to_bias as the fallback")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, q_len, h, d = q.shape
    assert q.shape[1] == k.shape[1], "sparse layouts are square"
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)

    key = (_config_key(sparsity_config), q_len, h, bool(causal), scale,
           bool(interpret))
    op = _OP_CACHE.get(key)
    if op is None:
        layout = np.asarray(sparsity_config.make_layout(q_len))
        if causal:
            layout = np.tril(layout)
        assert layout.shape[0] in (1, h), (layout.shape, h)
        if len(_OP_CACHE) >= _OP_CACHE_MAX:
            _OP_CACHE.pop(next(iter(_OP_CACHE)))
        op = make_sparse_op(layout, causal=causal, scale=scale,
                            block=int(sparsity_config.block), num_heads=h,
                            interpret=interpret)
        _OP_CACHE[key] = op

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o3 = op(to3(q), to3(k), to3(v))
    return o3.reshape(b, h, q_len, d).transpose(0, 2, 1, 3)
