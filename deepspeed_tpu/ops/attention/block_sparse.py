"""Pallas TPU block-sparse flash attention (fwd + bwd).

Reference: the Triton block-sparse attention kernels
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD/DDS :196-628,
``softmax.py`` :123) driven by SparsityConfig layouts — the reference's
long-sequence story (10x longer sequences, ~6x faster; BASELINE.md).

Design — RAGGED (CSR-style) grids with scalar prefetch:
  * the [heads, nq, nk] block layout is compiled (at trace time, on
    host) into per-head step lists: step s touches (row[h,s], col[h,s])
    with first/last flags marking row boundaries. The grid is
    ``(b*h, S)`` where ``S = nnz`` — one grid step per ACTIVE block, so
    both the MXU work and the k/v block DMA scale with the layout
    density. This is the Pallas equivalent of the Triton ``make_lut``.
    (An earlier revision padded every ROW to the max row population —
    one dense global row, as in BigBird/Longformer, then inflated the
    whole grid to dense size and measured SLOWER than dense at 32k.)
  * the step arrays ride as *scalar prefetch* operands (SMEM), so
    BlockSpec index maps can read them — the pipeline knows the next
    block's address ahead of time and keeps prefetching (a
    data-dependent ``pl.when`` skip would serialize Mosaic's double
    buffering).
  * with ``different_layout_per_head`` the per-head step counts differ;
    shorter heads pad to S with no-op steps that re-point the DMA at
    the previous block (no new traffic, no compute).
  * rows with no active blocks still emit one no-op step flagged
    first+last so their output block finalizes (to zeros, matching the
    dense kernel's fully-masked-row behavior).
  * causal masking stays in-kernel for diagonal blocks; callers pass
    layouts already lower-triangular for unidirectional patterns
    (flash_attention ANDs tril in).
  * backward follows flash-attention-2: dq over the same row-major
    steps; dk/dv over the transposed (column-major) steps.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from deepspeed_tpu.ops.attention.flash import (NEG_INF, _bwd_p_ds,
                                               _causal_block_mask,
                                               _finalize_softmax,
                                               _online_softmax_step)


def build_csr(layout, factor=1):
    """layout [H, n_rows, n_cols] -> per-head ragged step arrays.

    Returns (row, col, first, last, run, fmask), each [H, S] int32 with
    S = max over heads of (nnz + empty-row placeholders). Steps walk the
    layout row-major; ``first``/``last`` flag each row's boundary steps
    (scratch init / output finalize), ``run`` is 0 on placeholder and
    padding steps.

    ``factor`` > 1 COALESCES the walk onto a (factor x factor)-coarser
    grid: one step per coarse cell containing ANY active fine cell, with
    the fine activity packed into ``fmask`` row-major (bit r*factor + c
    = fine cell (r, c) inside the coarse tile; factor <= 5 fits int32).
    Small-block patterns (the reference's 128-block BigBird/Longformer)
    were per-grid-step-overhead bound on TPU (~13%% of their density
    ceiling); riding MXU-sized coarse tiles with exact in-kernel fine
    masks recovers the step economics WITHOUT changing the attention
    pattern."""
    H, n_rows, n_cols = layout.shape
    assert n_rows % factor == 0 and n_cols % factor == 0, \
        (layout.shape, factor)
    assert factor * factor <= 31, "fmask bits must fit an int32"
    heads = []
    for h in range(H):
        fine = np.asarray(layout[h], bool)
        if factor == 1:
            coarse = fine
        else:
            coarse = fine.reshape(n_rows // factor, factor,
                                  n_cols // factor, factor) \
                .any(axis=(1, 3))
        steps = []   # (row, col, first, last, run, fmask)
        for r in range(coarse.shape[0]):
            idx = np.nonzero(coarse[r])[0]
            if len(idx) == 0:
                steps.append((r, 0, 1, 1, 0, 0))
                continue
            n = len(idx)
            for t, c in enumerate(idx):
                if factor == 1:
                    fm = 1
                else:
                    sub = fine[r * factor:(r + 1) * factor,
                               c * factor:(c + 1) * factor]
                    fm = int(np.sum(sub.reshape(-1) *
                                    (1 << np.arange(factor * factor))))
                steps.append((r, int(c), int(t == 0), int(t == n - 1),
                              1, fm))
        heads.append(np.array(steps, np.int32))
    S = max(len(s) for s in heads)
    out = np.zeros((6, H, S), np.int32)
    for h, arr in enumerate(heads):
        out[:, h, :len(arr)] = arr.T
        if len(arr) < S:    # pad: re-point at the last block, all flags 0
            out[0, h, len(arr):] = arr[-1, 0]
            out[1, h, len(arr):] = arr[-1, 1]
    return tuple(out)


def _fine_mask(shape, fmask_bits, factor, fine, transposed=False):
    """Boolean [cblock, cblock] mask from the packed fine-activity bits
    (row-major bit r*factor + c per fine cell of size ``fine``).
    ``transposed``: the bits were packed from the TRANSPOSED layout (the
    dkv walk) but the score tile is in (q, k) orientation — read bit
    (c, r) instead."""
    fr = jax.lax.broadcasted_iota(jnp.int32, shape, 0) // fine
    fc = jax.lax.broadcasted_iota(jnp.int32, shape, 1) // fine
    bit = (fc * factor + fr) if transposed else (fr * factor + fc)
    return ((fmask_bits >> bit) & 1) == 1


def _head(i, num_heads, layout_heads):
    return jnp.mod(i, num_heads) if layout_heads > 1 else 0


# --------------------------------------------------------------------- fwd
def _fwd_kernel(row_ref, col_ref, first_ref, last_ref, run_ref, fmask_ref,
                q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, block, causal, num_heads,
                layout_heads, factor):
    s = pl.program_id(1)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(first_ref[h, s] == 1)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    qi = row_ref[h, s]
    ki = col_ref[h, s]
    run = run_ref[h, s] == 1
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if factor > 1:   # exact small-block pattern on the coarse tile
            sc = jnp.where(_fine_mask(sc.shape, fmask_ref[h, s], factor,
                                      block // factor), sc, NEG_INF)
        if causal:
            sc = _causal_block_mask(sc, qi, ki, block, block, 0)
        _online_softmax_step(sc, v, m_scr, l_scr, acc_scr)

    @pl.when(last_ref[h, s] == 1)
    def _finalize():
        _finalize_softmax(o_ref, lse_ref, m_scr, l_scr, acc_scr)


def _sparse_fwd(q3, k3, v3, csr, *, scale, block, causal, num_heads,
                interpret, factor=1):
    bh, q_len, d = q3.shape
    row, col, first, last, run, fmask = csr
    H, S = row.shape

    def at_row(i, s, row, col, first, last, run, fmask):
        return (i, row[_head(i, num_heads, H), s], 0)

    def at_col(i, s, row, col, first, last, run, fmask):
        return (i, col[_head(i, num_heads, H), s], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(bh, S),
        in_specs=[
            pl.BlockSpec((1, block, d), at_row),
            pl.BlockSpec((1, block, d), at_col),
            pl.BlockSpec((1, block, d), at_col),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), at_row),
            pl.BlockSpec((1, block, 1), at_row),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block=block, causal=causal,
        num_heads=num_heads, layout_heads=H, factor=factor)
    o, lse = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        interpret=interpret,
    )(row, col, first, last, run, fmask, q3, k3, v3)
    return o, lse


# --------------------------------------------------------------------- bwd
def _bwd_p_ds_fine(q, k, v, do, lse, delta, scale, causal, qi, ki, block,
                   factor, fmask_bits, transposed=False):
    """flash.py's shared _bwd_p_ds with the coarse tile's fine-activity
    mask threaded in as its score_mask (the fwd masked the same way, so
    p must be zero on inactive fine cells or dq/dk/dv pick up phantom
    mass). One numerics implementation — this is just the mask
    construction."""
    mask = _fine_mask((q.shape[0], k.shape[0]), fmask_bits, factor,
                      block // factor, transposed) if factor > 1 else None
    return _bwd_p_ds(q, k, v, do, lse, delta, scale, causal, qi, ki,
                     block, block, 0, score_mask=mask)


def _bwd_dq_kernel(row_ref, col_ref, first_ref, last_ref, run_ref,
                   fmask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, scale, block, causal,
                   num_heads, layout_heads, factor):
    s = pl.program_id(1)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(first_ref[h, s] == 1)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    qi = row_ref[h, s]
    ki = col_ref[h, s]
    run = run_ref[h, s] == 1
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, ds = _bwd_p_ds_fine(q, k, v, do, lse_ref[0], delta_ref[0],
                               scale, causal, qi, ki, block, factor,
                               fmask_ref[h, s])
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last_ref[h, s] == 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(row_ref, col_ref, first_ref, last_ref, run_ref,
                    fmask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                    block, causal, num_heads, layout_heads, factor):
    s = pl.program_id(1)
    h = _head(pl.program_id(0), num_heads, layout_heads)

    @pl.when(first_ref[h, s] == 1)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    # transposed walk: "row" is the k/v column block, "col" the q row;
    # the transposed fmask was packed from the transposed fine layout,
    # but _bwd_p_ds_fine computes s in (q, k) orientation — transpose
    # the bits back by swapping the r/c bit roles via a transposed mask
    ki = row_ref[h, s]
    qi = col_ref[h, s]
    run = run_ref[h, s] == 1
    if causal:
        run = jnp.logical_and(run, ki <= qi)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, ds = _bwd_p_ds_fine(q, k, v, do, lse_ref[0], delta_ref[0],
                               scale, causal, qi, ki, block, factor,
                               fmask_ref[h, s], transposed=True)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last_ref[h, s] == 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_bwd(q3, k3, v3, o3, lse, do3, csr, csr_t, *, scale, block,
                causal, num_heads, interpret, factor=1):
    bh, q_len, d = q3.shape
    row, col, first, last, run, fmask = csr
    row_t, col_t, first_t, last_t, run_t, fmask_t = csr_t
    H, S = row.shape
    St = row_t.shape[1]

    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def at_row(i, s, row, col, *_rest):
        return (i, row[_head(i, num_heads, H), s], 0)

    def at_col(i, s, row, col, *_rest):
        return (i, col[_head(i, num_heads, H), s], 0)

    grid_dq = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(bh, S),
        in_specs=[
            pl.BlockSpec((1, block, d), at_row),     # q
            pl.BlockSpec((1, block, d), at_col),     # k
            pl.BlockSpec((1, block, d), at_col),     # v
            pl.BlockSpec((1, block, d), at_row),     # do
            pl.BlockSpec((1, block, 1), at_row),     # lse
            pl.BlockSpec((1, block, 1), at_row),     # delta
        ],
        out_specs=pl.BlockSpec((1, block, d), at_row),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block=block,
                          causal=causal, num_heads=num_heads,
                          layout_heads=H, factor=factor),
        grid_spec=grid_dq,
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q3.dtype),
        interpret=interpret,
    )(row, col, first, last, run, fmask, q3, k3, v3, do3, lse, delta)

    grid_dkv = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(bh, St),
        in_specs=[
            pl.BlockSpec((1, block, d), at_col),     # q rows (transposed)
            pl.BlockSpec((1, block, d), at_row),     # k fixed column
            pl.BlockSpec((1, block, d), at_row),     # v
            pl.BlockSpec((1, block, d), at_col),     # do rows
            pl.BlockSpec((1, block, 1), at_col),     # lse
            pl.BlockSpec((1, block, 1), at_col),     # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), at_row),
            pl.BlockSpec((1, block, d), at_row),
        ],
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32),
                        pltpu.VMEM((block, d), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block=block,
                          causal=causal, num_heads=num_heads,
                          layout_heads=H, factor=factor),
        grid_spec=grid_dkv,
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, q_len, d), v3.dtype),
        ],
        interpret=interpret,
    )(row_t, col_t, first_t, last_t, run_t, fmask_t, q3, k3, v3, do3,
      lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------- entry
def make_sparse_op(layout, *, causal, scale, block, num_heads, interpret,
                   factor=1):
    """custom_vjp closing over the (static) layout's CSR step arrays.

    The step arrays stay NUMPY: the op is cached and reused across
    traces, and a jnp constant minted inside one trace (e.g. the first
    call under a caller's scan/fori_loop) would leak that trace's
    tracer into every later one.

    ``factor`` > 1 runs the kernels on (factor*block)-sized coarse
    tiles with the exact fine pattern applied in-kernel from packed
    bitmasks (build_csr): same attention function, MXU-sized steps."""
    csr = tuple(np.ascontiguousarray(a)
                for a in build_csr(layout, factor))
    csr_t = tuple(np.ascontiguousarray(a)
                  for a in build_csr(layout.transpose(0, 2, 1), factor))
    kw = dict(scale=scale, block=block * factor, causal=causal,
              num_heads=num_heads, interpret=interpret, factor=factor)

    @jax.custom_vjp
    def op(q3, k3, v3):
        o, _ = _sparse_fwd(q3, k3, v3, csr, **kw)
        return o

    def fwd(q3, k3, v3):
        o, lse = _sparse_fwd(q3, k3, v3, csr, **kw)
        return o, (q3, k3, v3, o, lse)

    def bwd(res, do):
        q3, k3, v3, o, lse = res
        return _sparse_bwd(q3, k3, v3, o, lse, do, csr, csr_t, **kw)

    op.defvjp(fwd, bwd)
    return op


_OP_CACHE = {}
_OP_CACHE_MAX = 64


def _config_key(cfg):
    def freeze(v):
        return tuple(v) if isinstance(v, (list, tuple)) else v
    return (type(cfg).__name__,) + tuple(
        (k, freeze(v)) for k, v in sorted(cfg.__dict__.items()))


def sparse_flash_attention(q, k, v, sparsity_config, *, causal=True,
                           scale=None, interpret=None):
    """Block-sparse attention on [batch, len, heads, head_dim] inputs,
    pattern from a SparsityConfig (ops/sparse_attention). Ops (and their
    host-built step arrays) are cached per (config, seq, heads, ...) so
    repeated calls/retraces skip the O(heads * blocks^2) layout
    compaction."""
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "block-sparse attention needs the Pallas TPU backend "
            "(jax.experimental.pallas.tpu); use mha_reference with "
            "layout_to_bias as the fallback")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, q_len, h, d = q.shape
    assert q.shape[1] == k.shape[1], "sparse layouts are square"
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)

    key = (_config_key(sparsity_config), q_len, h, bool(causal), scale,
           bool(interpret))
    op = _OP_CACHE.get(key)
    if op is None:
        layout = np.asarray(sparsity_config.make_layout(q_len))
        if causal:
            layout = np.tril(layout)
        assert layout.shape[0] in (1, h), (layout.shape, h)
        block = int(sparsity_config.block)
        # Coarse-tile coalescing (build_csr factor > 1, exact fine
        # bitmasks in-kernel) is implemented and oracle-tested, but
        # UNIFORM coarsening measured break-even for band patterns and
        # a REGRESSION for scattered ones on v5e (a lone random/global
        # 128-block lights a whole 512^2 tile: 16x padded compute —
        # bigbird128@32k went 3.74x -> 3.00x). It stays opt-in via
        # make_sparse_op(factor=...) until the hybrid two-pass (bands
        # coarse + scattered fine, lse-merged) lands; meanwhile
        # MXU-native patterns simply configure block >= 512.
        factor = 1
        if len(_OP_CACHE) >= _OP_CACHE_MAX:
            _OP_CACHE.pop(next(iter(_OP_CACHE)))
        op = make_sparse_op(layout, causal=causal, scale=scale,
                            block=block, num_heads=h,
                            interpret=interpret, factor=factor)
        _OP_CACHE[key] = op

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o3 = op(to3(q), to3(k), to3(v))
    return o3.reshape(b, h, q_len, d).transpose(0, 2, 1, 3)
