"""Attention ops: Pallas flash kernel + pure-jnp oracles.

Reference parity: csrc/transformer fused attention kernels and
deepspeed/ops/sparse_attention (block-sparse Triton) map here.
"""

from deepspeed_tpu.ops.attention.reference import (apply_rotary_emb,  # noqa: F401
                                                   causal_mask,
                                                   decode_attention_reference,
                                                   mha_reference)
from deepspeed_tpu.ops.attention.flash import flash_attention  # noqa: F401
from deepspeed_tpu.ops.attention.decode import (decode_attention,  # noqa: F401
                                                gather_pages,
                                                paged_decode_attention)
from deepspeed_tpu.ops.attention.ring import (ring_attention_local,  # noqa: F401
                                              ring_attention_sharded)
from deepspeed_tpu.ops.attention.ulysses import (  # noqa: F401
    ulysses_attention_local, ulysses_attention_sharded)
