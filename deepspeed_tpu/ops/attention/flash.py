"""Pallas TPU flash attention (forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(csrc/transformer/ds_transformer_cuda.cpp, softmax_kernels.cu) and the
Triton block-sparse path (deepspeed/ops/sparse_attention/matmul.py): one
online-softmax kernel that never materializes the [q_len, k_len] score
matrix in HBM.

Design:
  * grid = (batch*heads, q_blocks, k_blocks); the k axis is innermost so
    the online-softmax state (m, l, acc) lives in VMEM scratch carried
    across sequential grid steps.
  * fp32 softmax statistics regardless of input dtype; matmuls request
    ``preferred_element_type=float32`` so the MXU accumulates in fp32.
  * causal blocks that are fully masked are skipped (`pl.when`), giving the
    ~2x causal speedup.
  * backward = two kernels (dq; dk+dv) recomputing p from the saved
    logsumexp, flash-attention-2 style; when the whole sequence fits one
    block (nq == nk == 1, the common seq<=1024 training shape) a fused
    dq+dk+dv kernel runs instead — one score recompute and one exp feed
    all three grads (measured ~25% faster than the split pair on v5e).

The public entry :func:`flash_attention` falls back to interpret mode off
TPU, so the same code path is exercised by the CPU test mesh.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = pl.ANY

NEG_INF = float(-1e30)  # large-negative instead of -inf: keeps exp() exact-0
                        # without nan from (-inf) - (-inf)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-manual-axes of `like`, so
    pallas_call works under shard_map with check_vma=True (ring/Ulysses
    call the kernel per shard)."""
    try:
        vma = jax.typeof(like).vma
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _causal_block_mask(s, qi, ki, block_q, block_k, offset):
    """Apply the in-block causal mask to a score tile."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0) + offset
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _online_softmax_step(s, v, m_scr, l_scr, acc_scr):
    """One flash-attention online-softmax update of the (m, l, acc)
    scratch state with a new score tile `s` and value block `v`.
    Shared by the dense and block-sparse kernels — numerics fixes land
    in exactly one place."""
    m_prev = m_scr[:][:, :1]
    l_prev = l_scr[:][:, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # fully-masked rows: m_new stays at NEG_INF and exp(NEG_INF - NEG_INF)
    # would be 1 - force p/alpha to 0
    row_live = m_new > NEG_INF / 2
    alpha = jnp.where(row_live, jnp.exp(m_prev - m_new), 0.0)
    p = jnp.where(row_live, jnp.exp(s - m_new), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * alpha + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def _finalize_softmax(o_ref, lse_ref, m_scr, l_scr, acc_scr):
    l = l_scr[:][:, :1]
    l = jnp.where(l == 0.0, 1.0, l)       # fully-masked row -> zeros
    o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
    lse_ref[0] = m_scr[:][:, :1] + jnp.log(l)


def _bwd_p_ds(q, k, v, do, lse, delta, scale, causal, qi, ki, block_q,
              block_k, offset, score_mask=None):
    """Recompute p from the saved logsumexp and form ds (flash-2 style);
    shared by the dense and sparse backward kernels. ``score_mask``
    (optional bool tile) knocks out entries BEFORE the causal mask —
    the block-sparse coarse tiles pass their fine-activity mask here so
    the recompute matches the forward exactly."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if score_mask is not None:
        s = jnp.where(score_mask, s, NEG_INF)
    if causal:
        s = _causal_block_mask(s, qi, ki, block_q, block_k, offset)
    # fully-masked rows carry lse = NEG_INF; their p must be 0
    p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return p, ds


def _causal_valid(qi, ki, block_q, block_k, offset):
    """Whether block (qi, ki) has any unmasked entry under causal+offset."""
    max_q = qi * block_q + block_q - 1 + offset
    return max_q >= ki * block_k


def _chunk_suffix_mask(n_rows, chunk_len):
    """Causal mask for chunk c of the single-block column-split kernels:
    the query-row suffix starts at the chunk's first column, so entry
    (r, j) is valid iff r >= j. Shared by the forward and fused-backward
    chunk loops so the masking numerics live in one place."""
    return (jax.lax.broadcasted_iota(jnp.int32, (n_rows, chunk_len), 0) >=
            jax.lax.broadcasted_iota(jnp.int32, (n_rows, chunk_len), 1))


def _chunk_plan(q_len, k_len, causal, offset, for_bwd=False):
    """Number of k-chunks for the single-block causal kernels: the
    column-split skips the strictly-upper-triangle work chunk by chunk
    (compute/exp scale by (C+1)/2C), with no extra grid steps — the
    chunks unroll inside one kernel invocation. Measured on v5e at seq
    1024: forward is fastest at C=2 (305us vs 471 plain; C=4's extra
    value stitching regresses it), backward at C=4 (552us vs 780)."""
    if not causal or offset != 0 or q_len != k_len:
        return 1
    prefs = (4, 2) if for_bwd else (2,)
    for c in prefs:
        if q_len % c == 0 and q_len // c >= 256:
            return c
    return 1


# --------------------------------------------------------------------- forward
def _fwd_kernel_1blk_causal(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                            scale, chunks):
    """Whole-sequence-in-one-block causal forward. k/v are consumed in
    `chunks` column chunks; chunk c only involves query rows >= c*Lc, so
    the masked upper triangle is skipped at chunk granularity. All state
    is SSA values (no scratch): the grid is just (batch*heads,)."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    L = q.shape[0]
    Lc = L // chunks
    m = l = acc = None
    for c in range(chunks):
        r0 = c * Lc
        q_lo = q[r0:] if r0 else q
        s = jax.lax.dot_general(
            q_lo, k[r0:r0 + Lc], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _chunk_suffix_mask(L - r0, Lc)
        s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        if c == 0:
            m = m_cur
            p = jnp.where(mask, jnp.exp(s - m), 0.0)
            l = jnp.sum(p, axis=1, keepdims=True)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v[:Lc], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            m_prev = m[r0:]
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            l_new = l[r0:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_new = acc[r0:] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v[r0:r0 + Lc], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m = jnp.concatenate([m[:r0], m_new], axis=0)
            l = jnp.concatenate([l[:r0], l_new], axis=0)
            acc = jnp.concatenate([acc[:r0], acc_new], axis=0)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale, block_q, block_k, causal, offset, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    run = _causal_valid(qi, ki, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_block_mask(s, qi, ki, block_q, block_k, offset)
        _online_softmax_step(s, v, m_scr, l_scr, acc_scr)

    @pl.when(ki == nk - 1)
    def _finalize():
        _finalize_softmax(o_ref, lse_ref, m_scr, l_scr, acc_scr)


def _flash_fwd(q3, k3, v3, *, scale, block_q, block_k, causal, interpret):
    """q3/k3/v3: [bh, len, d] -> (o [bh, q_len, d], lse [bh, q_len])."""
    bh, q_len, d = q3.shape
    k_len = k3.shape[1]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    assert q_len % block_q == 0 and k_len % block_k == 0, \
        f"seq lens ({q_len},{k_len}) must be multiples of blocks " \
        f"({block_q},{block_k})"
    nq, nk = q_len // block_q, k_len // block_k
    offset = k_len - q_len

    chunks = _chunk_plan(q_len, k_len, causal, offset)
    if nq == 1 and nk == 1 and chunks > 1:
        spec_q = pl.BlockSpec((1, q_len, d), lambda i: (i, 0, 0))
        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_1blk_causal, scale=scale,
                              chunks=chunks),
            grid=(bh,),
            in_specs=[spec_q] * 3,
            out_specs=[spec_q,
                       pl.BlockSpec((1, q_len, 1), lambda i: (i, 0, 0))],
            out_shape=[
                _sds((bh, q_len, d), q3.dtype, q3),
                _sds((bh, q_len, 1), jnp.float32, q3),
            ],
            interpret=interpret,
        )(q3, k3, v3)
        return o, lse

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, offset=offset, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, k: (i, k, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, k: (i, k, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, k: (i, j, 0)),
            # lse rides as [bh, q_len, 1]: TPU blocks need their last two
            # dims (8,128)-divisible or array-spanning
            pl.BlockSpec((1, block_q, 1), lambda i, j, k: (i, j, 0)),
        ],
        out_shape=[
            _sds((bh, q_len, d), q3.dtype, q3),
            _sds((bh, q_len, 1), jnp.float32, q3),
        ],
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((block_q, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((block_q, 128), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


# -------------------------------------------------------------------- backward
def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *,
                      scale, block_q, block_k, causal, offset, chunks=1):
    """Single-block fused backward (nq == nk == 1): one score recompute +
    one exp feed dq, dk AND dv — 5 matmuls instead of the split kernels'
    7 (and half the exp traffic). With `chunks` > 1 (causal, q_len ==
    k_len) the k axis is processed in column chunks over shrinking query
    row suffixes, skipping the masked upper triangle like the chunked
    forward. The split dq/dkv pair below remains the general tiled path."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    if chunks == 1:
        p, ds = _bwd_p_ds(q, k, v, do, lse, delta, scale, causal, 0, 0,
                          block_q, block_k, offset)
        dv_ref[0] = jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dk_ref[0] = jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        dq_ref[0] = jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        return

    L = q.shape[0]
    Lc = L // chunks
    dq = None
    for c in range(chunks):
        r0 = c * Lc
        q_lo = q[r0:] if r0 else q
        do_lo = do[r0:] if r0 else do
        lse_lo = lse[r0:] if r0 else lse
        delta_lo = delta[r0:] if r0 else delta
        k_c = k[r0:r0 + Lc]
        v_c = v[r0:r0 + Lc]
        s = jax.lax.dot_general(
            q_lo, k_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _chunk_suffix_mask(L - r0, Lc)
        p = jnp.where(mask, jnp.exp(s - lse_lo), 0.0)
        dp = jax.lax.dot_general(
            do_lo, v_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_lo) * scale
        dv_ref[0, r0:r0 + Lc] = jax.lax.dot_general(
            p.astype(do.dtype), do_lo, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dk_ref[0, r0:r0 + Lc] = jax.lax.dot_general(
            ds.astype(q.dtype), q_lo, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        dq_add = jax.lax.dot_general(
            ds.astype(k.dtype), k_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dq is None:
            dq = dq_add
        else:
            dq = jnp.concatenate([dq[:r0], dq[r0:] + dq_add], axis=0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale, block_q, block_k, causal, offset, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    run = _causal_valid(qi, ki, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]          # (block_q, 1)
        delta = delta_ref[0]      # (block_q, 1)
        p, ds = _bwd_p_ds(q, k, v, do, lse, delta, scale, causal, qi, ki,
                          block_q, block_k, offset)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    scale, block_q, block_k, causal, offset, nq):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    run = _causal_valid(qi, ki, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]          # (block_q, 1)
        delta = delta_ref[0]      # (block_q, 1)
        p, ds = _bwd_p_ds(q, k, v, do, lse, delta, scale, causal, qi, ki,
                          block_q, block_k, offset)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, *, scale, block_q, block_k, causal,
               interpret, dlse=None):
    bh, q_len, d = q3.shape
    k_len = k3.shape[1]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    nq, nk = q_len // block_q, k_len // block_k
    offset = k_len - q_len

    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1,
                    keepdims=True)  # (bh, q_len, 1) to match lse layout
    if dlse is not None:
        # cotangent of the logsumexp output: d lse / d s = p, so it folds
        # into ds = p*(dp - delta + dlse)*scale, i.e. delta -= dlse
        delta = delta - dlse.astype(jnp.float32)

    if nq == 1 and nk == 1:
        # whole sequence in one block: fused dq/dk/dv kernel (one score
        # recompute, one exp)
        spec_q = pl.BlockSpec((1, block_q, d), lambda i: (i, 0, 0))
        spec_k = pl.BlockSpec((1, block_k, d), lambda i: (i, 0, 0))
        spec_r = pl.BlockSpec((1, block_q, 1), lambda i: (i, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale,
                              block_q=block_q, block_k=block_k,
                              causal=causal, offset=offset,
                              chunks=_chunk_plan(q_len, k_len, causal,
                                                 offset, for_bwd=True)),
            grid=(bh,),
            in_specs=[spec_q, spec_k, spec_k, spec_q, spec_r, spec_r],
            out_specs=[spec_q, spec_k, spec_k],
            out_shape=[
                _sds((bh, q_len, d), q3.dtype, q3),
                _sds((bh, k_len, d), k3.dtype, k3),
                _sds((bh, k_len, d), v3.dtype, v3),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
        return dq, dk, dv

    q_spec = pl.BlockSpec((1, block_q, d), lambda i, j, k: (i, j, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda i, j, k: (i, k, 0))
    r_spec = pl.BlockSpec((1, block_q, 1), lambda i, j, k: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, offset=offset, nk=nk),
        grid=(bh, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, k: (i, j, 0)),
        out_shape=_sds((bh, q_len, d), q3.dtype, q3),
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    # dkv grid: k outer, q inner (accumulate over q)
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda i, k, j: (i, j, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda i, k, j: (i, k, 0))
    r_spec2 = pl.BlockSpec((1, block_q, 1), lambda i, k, j: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, offset=offset, nq=nq),
        grid=(bh, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, k, j: (i, k, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, k, j: (i, k, 0)),
        ],
        out_shape=[
            _sds((bh, k_len, d), k3.dtype, k3),
            _sds((bh, k_len, d), v3.dtype, v3),
        ],
        scratch_shapes=[
            pl.ANY if pltpu is None else pltpu.VMEM((block_k, d), jnp.float32),
            pl.ANY if pltpu is None else pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public entry
@functools.lru_cache(maxsize=None)
def _make_op_with_lse(causal, scale, block_q, block_k, interpret):
    """Like _make_op but returns (o, lse) with gradients flowing through
    BOTH (the ring-attention hop contract: downstream log-sum-exp merges
    consume lse)."""

    @jax.custom_vjp
    def op(q3, k3, v3):
        return _flash_fwd(q3, k3, v3, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal,
                          interpret=interpret)

    def fwd(q3, k3, v3):
        o, lse = _flash_fwd(q3, k3, v3, scale=scale, block_q=block_q,
                            block_k=block_k, causal=causal,
                            interpret=interpret)
        return (o, lse), (q3, k3, v3, o, lse)

    def bwd(res, cots):
        do, dlse = cots
        q3, k3, v3, o, lse = res
        return _flash_bwd(q3, k3, v3, o, lse, do, scale=scale,
                          block_q=block_q, block_k=block_k, causal=causal,
                          interpret=interpret, dlse=dlse)

    op.defvjp(fwd, bwd)
    return op


def flash_attention_with_lse(q3, k3, v3, *, causal, scale, block,
                             interpret=None):
    """[bh, len, d] flash attention returning (o, lse [bh, len, 1]),
    differentiable in both outputs (the lse cotangent folds into the
    backward's delta term). The o-only public entry routes through the
    same op — one factory, one numerics implementation."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    op = _make_op_with_lse(bool(causal), float(scale), int(block),
                           int(block), bool(interpret))
    return op(q3, k3, v3)


def _pick_block(seq_len, target=1024):
    """Largest block <= target that divides seq_len. Grid-step overhead
    on the Mosaic pipeline dominates small blocks: at seq 1024 on v5e,
    128-blocks measured ~4x slower than 512s and 512s ~1.7x slower than
    one whole-seq 1024 block (fwd 811us -> 471us, fwd+bwd 1423us ->
    994us), so the target is 1024; longer sequences tile at 1024 where
    the fp32 score block (1024x1024 = 4 MB) still fits VMEM comfortably
    alongside the double-buffered operands."""
    for b in (target, 512, 384, 256, 128):
        if b <= seq_len and seq_len % b == 0:
            return b
    return seq_len


def flash_attention(q, k, v, *, causal=True, scale=None, block_q=None,
                    block_k=None, interpret=None, sparsity_config=None,
                    with_lse=False):
    """Flash attention on [batch, len, heads, head_dim] inputs.

    Drop-in for :func:`ops.attention.reference.mha_reference` (the oracle).
    `interpret=None` auto-selects interpret mode off-TPU so CPU tests run
    the same kernel. Block sizes default to the largest divisor of the seq
    len up to 512 (see :func:`_pick_block`).

    ``sparsity_config`` (ops/sparse_attention SparsityConfig) routes to
    the block-sparse kernel (block_sparse.py): grid steps exist only for
    active blocks, so compute AND k/v traffic scale with layout density.
    """
    if q.dtype == jnp.float16 and jax.default_backend() == "tpu":
        # fp16 -> jnp-oracle FALLBACK (the documented contract, not an
        # accident): Mosaic has no f16 vector type on TPU ("Unsupported
        # type in mosaic dialect: 'f16'"), so fp16 inputs can never reach
        # the Pallas kernel. XLA itself handles f16 by upcasting, so fp16
        # compat mode routes through mha_reference — which MATERIALIZES
        # the [q_len, k_len] score matrix in HBM. Cost: O(l^2) memory and
        # no online-softmax fusion, i.e. fp16 attention loses the entire
        # flash win; it exists so torch-parity fp16 configs run at all.
        # bf16 is the TPU-native half type — use it for any run where
        # attention speed matters (the inference engine and benchmarks
        # default to bf16 for exactly this reason).
        assert not with_lse, \
            "fp16 attention has no kernel lse path on TPU; use bf16 " \
            "for sequence-parallel training (the TPU-native half type)"
        if sparsity_config is not None:
            # no tril here: mha_reference applies the element-level
            # causal mask itself when causal=True, and bidirectional
            # layouts (causal=False) must keep their forward blocks
            from deepspeed_tpu.ops.sparse_attention import layout_to_bias
            layout = np.asarray(sparsity_config.make_layout(q.shape[1]))
            bias = layout_to_bias(layout, q.shape[1],
                                  int(sparsity_config.block))
            from deepspeed_tpu.ops.attention.reference import mha_reference
            return mha_reference(q, k, v, causal=causal, bias=bias,
                                 scale=scale)
        from deepspeed_tpu.ops.attention.reference import mha_reference
        return mha_reference(q, k, v, causal=causal, scale=scale)
    if sparsity_config is not None:
        assert not with_lse, "with_lse is not supported on the sparse path"
        from deepspeed_tpu.ops.attention.block_sparse import (
            sparse_flash_attention)
        return sparse_flash_attention(q, k, v, sparsity_config,
                                      causal=causal, scale=scale,
                                      interpret=interpret)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, q_len, h, d = q.shape
    if block_q is None:
        block_q = _pick_block(q_len)
    if block_k is None:
        block_k = _pick_block(k.shape[1])
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)

    def to3(x):
        # [b, l, h, d] -> [b*h, l, d] layout change feeding the kernel's
        # (batch*heads, q_blocks, k_blocks) grid. Measured cost: ~2.5% of
        # the fused attention on the CPU rig at gpt2-small bench shapes
        # (3 x 17ms vs 2.06s), and bounded analytically on TPU by 6 HBM
        # passes over q/k/v (~75 MB bf16 at [8,1024,12,64] ≈ 0.1 ms at
        # ~800 GB/s) against an O(l^2) compute kernel — negligible, which
        # is why the kernel takes the transposed layout instead of
        # carrying strided BlockSpecs.
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    op = _make_op_with_lse(bool(causal), scale, int(block_q), int(block_k),
                           bool(interpret))
    o3, lse3 = op(to3(q), to3(k), to3(v))
    o = o3.reshape(b, h, q_len, d).transpose(0, 2, 1, 3)
    if with_lse:
        return o, lse3.reshape(b, h, q_len)
    return o
