"""Ring attention: context parallelism over the `sequence` mesh axis.

Fills the reference's long-context gap (SURVEY.md §5.7: v0.8.3 has no ring
attention / context parallelism — only block-sparse kernels). Design is the
blockwise-attention ring of Liu et al. (Ring Attention) mapped to the TPU
ICI torus: every device holds one sequence chunk of q/k/v; k/v chunks hop
around the ring via ``lax.ppermute`` while each device accumulates online
softmax statistics for its local queries — so peak memory is O(L/P) per
device and the N^2 score matrix never materializes.

Causality is handled by absolute chunk offsets: a device skips nothing
structurally (static schedule), it just masks chunks ahead of its queries.

Used inside ``shard_map`` over the `sequence` axis;
:func:`ring_attention_sharded` wraps that for [b, l, h, d] global arrays.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_INF = float(-1e30)


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def _flash_chunk(q, k, v, *, causal, scale):
    """One chunk-vs-chunk attention returning (normalized output
    [b,c,h,d], lse [b,h,c]); differentiable in both (the lse cotangent
    folds into the kernel's backward). On TPU this is the Pallas flash
    kernel; off-TPU a dense jnp computation — the Pallas interpreter's
    internal dynamic_slices would trip shard_map's varying-axes checker,
    and keeping check_vma ON matters more than interpret-mode fidelity."""
    if jax.default_backend() == "tpu":
        from deepspeed_tpu.ops.attention.flash import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               with_lse=True)
    b, c, h, d = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((c, k.shape[1]), bool))
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = logits.max(axis=-1)
    w = jnp.exp(logits - m[..., None])
    s = w.sum(axis=-1)
    lse = m + jnp.log(s)
    out = jnp.einsum("bhqk,bkhd->bqhd", (w / s[..., None]).astype(v.dtype),
                     v)
    return out.astype(jnp.float32), lse


def ring_attention_local(q, k, v, axis_name, *, causal=True, scale=None,
                         init=None):
    """Per-shard body (call under shard_map, sequence-sharded on dim 1).

    q/k/v: [b, chunk, h, d] local chunks. Returns [b, chunk, h, d].

    ``init`` optionally seeds the online-softmax carries ``(m, l, acc)``
    (shapes [b, chunk, h] / [b, chunk, h] / [b, chunk, h, d], fp32) with
    statistics of an already-attended block — the sequence-parallel
    prefill path folds the paged PREFIX in this way, so the ring only
    hops the fresh chunk.  The carries must be derived from q (vma).

    Each hop's chunk-vs-chunk product runs through the Pallas flash
    kernel (fp32 softmax statistics in VMEM; no [chunk, chunk] fp32
    score tensor in HBM), and hops are merged by log-sum-exp
    combination of per-hop (output, lse). The chunk relation picks the
    kernel via ``lax.switch`` — fully-behind chunks use the dense
    kernel, the diagonal uses the causal kernel, fully-ahead chunks are
    skipped (no compute). k/v hop the ring in their INPUT dtype (bf16
    in mixed-precision models — half the ICI bytes of fp32), and the
    ppermute for hop i+1 is issued before hop i's compute, so the
    collective overlaps the kernel under XLA's latency-hiding scheduler.
    """
    b, chunk, h, d = q.shape
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def hop_attention(k_cur, v_cur, i):
        """(o, lse) of local q against the hop-i chunk."""
        src = (my_idx - i) % n

        def skip(args):
            q, k_cur, v_cur = args
            o = jnp.zeros_like(q, jnp.float32)
            lse = jnp.full((b, h, chunk), NEG_INF, jnp.float32) + \
                0.0 * q[..., 0].transpose(0, 2, 1).astype(jnp.float32)
            return o, lse

        def diag(args):
            q, k_cur, v_cur = args
            o, lse = _flash_chunk(q, k_cur, v_cur, causal=True, scale=scale)
            return o.astype(jnp.float32), lse

        def full(args):
            q, k_cur, v_cur = args
            o, lse = _flash_chunk(q, k_cur, v_cur, causal=False, scale=scale)
            return o.astype(jnp.float32), lse

        if not causal:
            return full((q, k_cur, v_cur))
        # 0: chunk is ahead of queries (skip), 1: diagonal, 2: behind
        branch = jnp.where(src == my_idx, 1,
                           jnp.where(src < my_idx, 2, 0))
        # the switch operands vary over every manual mesh axis q does
        # (data/model/...); the index only varies over the ring axis —
        # broadcast its varying-axes set so the vma checker accepts it
        q_vma = getattr(jax.typeof(q), "vma", frozenset())
        b_vma = getattr(jax.typeof(branch), "vma", frozenset())
        missing = tuple(q_vma - b_vma)
        if missing:
            # lax.pvary is deprecated in favor of pcast(to='varying');
            # keep the fallback for jax versions that predate pcast
            if hasattr(lax, "pcast"):
                branch = lax.pcast(branch, missing, to="varying")
            else:   # pragma: no cover
                branch = lax.pvary(branch, missing)
        return lax.switch(branch, [skip, diag, full], (q, k_cur, v_cur))

    def merge(m, l, acc, o_i, lse_i):
        """Log-sum-exp merge of a new hop into the running output."""
        lse_q = lse_i.transpose(0, 2, 1)                  # [b, c, h]
        m_new = jnp.maximum(m, lse_q)
        live = m_new > NEG_INF / 2
        alpha = jnp.where(live, jnp.exp(m - m_new), 0.0)
        beta = jnp.where(live, jnp.exp(lse_q - m_new), 0.0)
        l_new = l * alpha + beta
        acc_new = acc * alpha[..., None] + o_i * beta[..., None]
        return m_new, l_new, acc_new

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # issue next hop first: no data dependence on this hop's compute,
        # so the ICI transfer overlaps the flash kernel
        k_nxt = lax.ppermute(k_cur, axis_name, _ring_perm(n))
        v_nxt = lax.ppermute(v_cur, axis_name, _ring_perm(n))
        o_i, lse_i = hop_attention(k_cur, v_cur, i)
        m, l, acc = merge(m, l, acc, o_i, lse_i)
        return (m, l, acc, k_nxt, v_nxt), None

    if init is None:
        # derive initial carries from q so they inherit its device-varying
        # axes (a plain jnp.zeros would be "unvarying" and trip shard_map's
        # scan carry type check whenever extra mesh axes like `data` are
        # manual)
        svar = 0.0 * q[..., 0].astype(jnp.float32)        # [b, c, h]
        m0 = jnp.full((b, chunk, h), NEG_INF, jnp.float32) + svar
        l0 = svar
        acc0 = jnp.zeros((b, chunk, h, d), jnp.float32) + svar[..., None]
    else:
        m0, l0, acc0 = init
    # n-1 hop-and-accumulate steps, then a final accumulate with no hop
    # (the last ppermute's result would be thrown away)
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n - 1))
    o_i, lse_i = hop_attention(k_last, v_last, n - 1)
    m, l, acc = merge(m, l, acc, o_i, lse_i)
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)           # [b, c, h, d]


def _bhd_spec(mesh, q_shape, axis):
    """[b, l, h, d] spec composing with the data (batch) and model (heads)
    axes when they exist and divide — so the op drops into an engine-jitted
    program without forcing replication."""
    def use(ax, dim):
        return ax if ax in mesh.shape and mesh.shape[ax] > 1 and \
            dim % mesh.shape[ax] == 0 else None
    return P(use("data", q_shape[0]), axis, use("model", q_shape[2]), None)


def ring_prefill_attention_local(q, k, v, k_pref, v_pref, prefix_len,
                                 axis_name, *, scale=None):
    """Per-shard body for one sequence-parallel PREFILL chunk, ring
    transport (heads need not divide the axis).

    q/k/v: [b, L/P, h, d] — the chunk, sequence-sharded on dim 1;
    k_pref/v_pref: [b, maxT, h, d] — the paged-pool gather, replicated
    over the sequence axis (every rank attends ALL its local heads
    against the full prefix); prefix_len: valid prefix rows.

    The prefix is a prologue, not a hop: its online-softmax statistics
    (m, l, acc) seed the ring carries, then the chunk hops the ring
    exactly like :func:`ring_attention_local`.  The prefix sits entirely
    BEHIND every query (chunk absolute positions start at prefix_len),
    so its only mask is ``col < prefix_len`` — which also excludes the
    chunk's own just-written pool rows.  ``prefix_len == 0`` degrades
    for free: the all-masked prologue yields m = NEG_INF carries, the
    exact empty seed the ring uses, and the merge's ``live`` guard
    zeroes the fake mass."""
    b, c, h, d = q.shape
    scale_ = scale if scale is not None else 1.0 / (d ** 0.5)
    maxT = k_pref.shape[1]
    logits_p = jnp.einsum("bqhd,bkhd->bhqk", q, k_pref,
                          preferred_element_type=jnp.float32) * scale_
    live = (jnp.arange(maxT) < prefix_len)[None, None, None, :]
    logits_p = jnp.where(live, logits_p, NEG_INF)
    mh = logits_p.max(axis=-1)                            # [b, h, c]
    live_q = mh > NEG_INF / 2
    p = jnp.where(live_q[..., None],
                  jnp.exp(logits_p - mh[..., None]), 0.0)
    l0 = p.sum(axis=-1)                                   # [b, h, c]
    acc0 = jnp.einsum("bhqk,bkhd->bqhd", p,
                      v_pref.astype(jnp.float32))         # [b, c, h, d]
    init = (mh.transpose(0, 2, 1), l0.transpose(0, 2, 1), acc0)
    return ring_attention_local(q, k, v, axis_name, causal=True,
                                scale=scale, init=init)


def ring_prefill_attention(q, k, v, k_pref, v_pref, prefix_len, mesh, *,
                           axis="sequence", scale=None):
    """Sequence-parallel prefill chunk attention against a paged prefix,
    ring transport.  q/k/v [b, L, h, d] (L shards over ``axis``);
    k_pref/v_pref [b, maxT, h, d] stay sequence-replicated."""
    spec = _bhd_spec(mesh, q.shape, axis)
    pspec = P(spec[0], None, spec[2], None)
    fn = functools.partial(ring_prefill_attention_local, axis_name=axis,
                           scale=scale)
    sharded = jax.shard_map(fn, mesh=mesh,
                            in_specs=(spec, spec, spec, pspec, pspec, P()),
                            out_specs=spec)
    return sharded(q, k, v, k_pref, v_pref, prefix_len)


def ring_attention_sharded(q, k, v, mesh, *, axis="sequence", causal=True,
                           scale=None):
    """Global entry: q/k/v [b, L, h, d] jax.Arrays; shards L over `axis`."""
    spec = _bhd_spec(mesh, q.shape, axis)
    fn = functools.partial(ring_attention_local, axis_name=axis,
                           causal=causal, scale=scale)
    # check_vma stays ON (VERDICT r2 weak #6): the ring body aligns the
    # switch index's varying axes itself (see hop_attention), so the
    # type discipline that guards the rest of the pipeline code also
    # covers the op with the trickiest collective pattern
    sharded = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec)
    return sharded(q, k, v)
