"""Ring attention: context parallelism over the `sequence` mesh axis.

Fills the reference's long-context gap (SURVEY.md §5.7: v0.8.3 has no ring
attention / context parallelism — only block-sparse kernels). Design is the
blockwise-attention ring of Liu et al. (Ring Attention) mapped to the TPU
ICI torus: every device holds one sequence chunk of q/k/v; k/v chunks hop
around the ring via ``lax.ppermute`` while each device accumulates online
softmax statistics for its local queries — so peak memory is O(L/P) per
device and the N^2 score matrix never materializes.

Causality is handled by absolute chunk offsets: a device skips nothing
structurally (static schedule), it just masks chunks ahead of its queries.

Used inside ``shard_map`` over the `sequence` axis;
:func:`ring_attention_sharded` wraps that for [b, l, h, d] global arrays.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_INF = float(-1e30)


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention_local(q, k, v, axis_name, *, causal=True, scale=None):
    """Per-shard body (call under shard_map, sequence-sharded on dim 1).

    q/k/v: [b, chunk, h, d] local chunks. Returns [b, chunk, h, d].
    """
    b, chunk, h, d = q.shape
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32)
    q_pos = my_idx * chunk + jnp.arange(chunk)            # absolute positions

    def accumulate(m, l, acc, k_cur, v_cur, i):
        # k_cur originated on device (my_idx - i) mod n
        src = (my_idx - i) % n
        k_pos = src * chunk + jnp.arange(chunk)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]       # [chunk, chunk]
            s = jnp.where(mask[None, None], s, NEG_INF)

        m_cur = jnp.max(s, axis=-1)                       # [b, h, q]
        m_new = jnp.maximum(m, m_cur)
        live = m_new > NEG_INF / 2
        alpha = jnp.where(live, jnp.exp(m - m_new), 0.0)
        p = jnp.where(live[..., None], jnp.exp(s - m_new[..., None]), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))
        return m_new, l_new, acc * alpha[..., None] + pv

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = accumulate(m, l, acc, k_cur, v_cur, i)
        k_nxt = lax.ppermute(k_cur, axis_name, _ring_perm(n))
        v_nxt = lax.ppermute(v_cur, axis_name, _ring_perm(n))
        return (m, l, acc, k_nxt, v_nxt), None

    # derive initial carries from q so they inherit its device-varying axes
    # (a plain jnp.zeros would be "unvarying" and trip shard_map's scan
    # carry type check whenever extra mesh axes like `data` are manual)
    qT = q32.transpose(0, 2, 1, 3)                        # [b, h, chunk, d]
    m0 = jnp.full((b, h, chunk), NEG_INF, jnp.float32) + 0.0 * qT[..., 0]
    l0 = 0.0 * qT[..., 0]
    acc0 = 0.0 * qT
    # n-1 hop-and-accumulate steps, then a final accumulate with no hop
    # (the last ppermute's result would be thrown away)
    (m, l, acc, k_last, v_last), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n - 1))
    m, l, acc = accumulate(m, l, acc, k_last, v_last, n - 1)
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]                              # [b, h, q, d]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _bhd_spec(mesh, q_shape, axis):
    """[b, l, h, d] spec composing with the data (batch) and model (heads)
    axes when they exist and divide — so the op drops into an engine-jitted
    program without forcing replication."""
    def use(ax, dim):
        return ax if ax in mesh.shape and mesh.shape[ax] > 1 and \
            dim % mesh.shape[ax] == 0 else None
    return P(use("data", q_shape[0]), axis, use("model", q_shape[2]), None)


def ring_attention_sharded(q, k, v, mesh, *, axis="sequence", causal=True,
                           scale=None):
    """Global entry: q/k/v [b, L, h, d] jax.Arrays; shards L over `axis`."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    spec = _bhd_spec(mesh, q.shape, axis)
    fn = functools.partial(ring_attention_local, axis_name=axis,
                           causal=causal, scale=scale)
    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)
