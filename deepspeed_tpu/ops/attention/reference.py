"""Reference (pure-jnp) attention implementations.

These are the correctness oracles for the Pallas kernels (reference test
style: each CUDA op tested against an eager torch implementation,
``tests/unit/ops/**``). They are also the fallback path on platforms without
Pallas support (CPU test mesh).
"""

import jax.numpy as jnp
from jax import lax


def causal_mask(q_len, k_len, dtype=jnp.float32, offset=0):
    """Additive causal mask; query i attends to keys <= i + offset."""
    q_idx = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 0)
    k_idx = lax.broadcasted_iota(jnp.int32, (q_len, k_len), 1)
    mask = k_idx <= (q_idx + offset)
    return jnp.where(mask, 0.0, jnp.finfo(dtype).min).astype(dtype)


def mha_reference(q, k, v, *, causal=True, bias=None, scale=None,
                  segment_ids=None):
    """Multi-head attention, [batch, len, heads, head_dim] layout.

    Softmax statistics accumulate in fp32 regardless of input dtype
    (matches the numerics the Pallas flash kernel keeps on TPU).
    """
    b, q_len, h, d = q.shape
    k_len = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        logits = logits + causal_mask(q_len, k_len, jnp.float32,
                                      offset=k_len - q_len)[None, None]
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, jnp.finfo(jnp.float32).min)
    weights = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    weights = weights / weights.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
    return out.astype(q.dtype)


def decode_attention_reference(q, k_cache, v_cache, cache_len, *, scale=None):
    """Single-token decode attention against a KV cache.

    q: [batch, 1, heads, dim]; caches: [batch, max_len, heads, dim];
    cache_len: [batch] valid lengths (int32). Reference equivalent of the
    CUDA ``softmax_context`` kernel (csrc/transformer/inference).
    """
    b, _, h, d = q.shape
    max_len = k_cache.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    pos = lax.broadcasted_iota(jnp.int32, (b, 1, 1, max_len), 3)
    valid = pos < cache_len[:, None, None, None]
    logits = jnp.where(valid, logits, jnp.finfo(jnp.float32).min)
    weights = jnp.exp(logits - logits.max(axis=-1, keepdims=True))
    weights = weights / weights.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v_cache.dtype), v_cache)
    return out.astype(q.dtype)


def apply_rotary_emb(x, positions, *, base=10000.0):
    """Rotary position embeddings, [batch, len, heads, dim] layout,
    rotate-half convention (Llama/GPT-NeoX; reference kernel:
    csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu)."""
    d = x.shape[-1]
    half = d // 2
    freq = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freq  # [b, l, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def apply_rotary_emb_interleaved(x, positions, *, base=10000.0):
    """GPT-J's rotate-every-two convention: pairs are (x[2i], x[2i+1])
    instead of (x[i], x[i+half])."""
    d = x.shape[-1]
    half = d // 2
    freq = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freq
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)


def apply_partial_rotary(x, positions, rotary_dim, *, base=10000.0,
                         interleaved=False):
    """Rotary on the first `rotary_dim` features only (GPT-J rotary_dim,
    GPT-NeoX rotary_pct); the rest pass through."""
    rot = x[..., :rotary_dim]
    rest = x[..., rotary_dim:]
    fn = apply_rotary_emb_interleaved if interleaved else apply_rotary_emb
    return jnp.concatenate([fn(rot, positions, base=base), rest], axis=-1)
