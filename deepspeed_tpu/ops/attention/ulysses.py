"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

DeepSpeed-Ulysses (postdates the reference snapshot; SURVEY.md §5.7 marks
it as the gap to fill): attention inputs arrive sequence-sharded
[b, L/P, h, d]; an all-to-all re-shards to head-sharded [b, L, h/P, d] so
each device runs *full-sequence* attention on a subset of heads (any
kernel works locally — including the Pallas flash kernel), then an inverse
all-to-all restores sequence sharding. Communication volume is O(L·h·d/P)
per device vs allgather's O(L·h·d).

Requires num_heads % P == 0.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.reference import mha_reference


def ulysses_attention_local(q, k, v, axis_name, *, causal=True,
                            attn_fn=None):
    """Per-shard body (under shard_map; inputs [b, chunk, h, d])."""
    attn_fn = attn_fn or (lambda q, k, v: mha_reference(q, k, v,
                                                        causal=causal))

    def seq_to_heads(x):
        # [b, L/P, h, d] -> [b, L, h/P, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attn_fn(qh, kh, vh)
    return heads_to_seq(oh)


def ulysses_attention_sharded(q, k, v, mesh, *, axis="sequence", causal=True,
                              attn_fn=None):
    """Global entry: q/k/v [b, L, h, d]; shards L over `axis`, swaps to
    heads for compute (DistributedAttention in deepspeed/sequence/layer.py
    of later snapshots)."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    assert q.shape[2] % n == 0, \
        f"num_heads {q.shape[2]} must divide sequence axis size {n}"
    from deepspeed_tpu.ops.attention.ring import _bhd_spec
    spec = _bhd_spec(mesh, q.shape, axis)
    if spec[2] is not None:
        # heads already model-sharded: the per-shard head count must still
        # divide the sequence axis for the all-to-all swap
        assert (q.shape[2] // mesh.shape["model"]) % n == 0, \
            "heads per model shard must divide the sequence axis size"
    fn = functools.partial(ulysses_attention_local, axis_name=axis,
                           causal=causal, attn_fn=attn_fn)
    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)
