"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

DeepSpeed-Ulysses (postdates the reference snapshot; SURVEY.md §5.7 marks
it as the gap to fill): attention inputs arrive sequence-sharded
[b, L/P, h, d]; an all-to-all re-shards to head-sharded [b, L, h/P, d] so
each device runs *full-sequence* attention on a subset of heads (any
kernel works locally — including the Pallas flash kernel), then an inverse
all-to-all restores sequence sharding. Communication volume is O(L·h·d/P)
per device vs allgather's O(L·h·d).

Requires num_heads % P == 0.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.reference import mha_reference
from deepspeed_tpu.ops.attention.ring import NEG_INF, _bhd_spec


def ulysses_attention_local(q, k, v, axis_name, *, causal=True,
                            attn_fn=None):
    """Per-shard body (under shard_map; inputs [b, chunk, h, d])."""
    attn_fn = attn_fn or (lambda q, k, v: mha_reference(q, k, v,
                                                        causal=causal))

    def seq_to_heads(x):
        # [b, L/P, h, d] -> [b, L, h/P, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attn_fn(qh, kh, vh)
    return heads_to_seq(oh)


def ulysses_prefill_attention_local(q, k, v, k_pref, v_pref, prefix_len,
                                    axis_name, *, scale=None):
    """Per-shard body for one sequence-parallel PREFILL chunk.

    q/k/v: [b, L/P, h, d] — the chunk, sequence-sharded on dim 1;
    k_pref/v_pref: [b, maxT, h/P, d] — the paged-pool gather,
    head-sharded over the SEQUENCE axis (rank j holds exactly the head
    block its all-to-all output computes, see the sharded entry);
    prefix_len: valid prefix rows (everything at position >= prefix_len
    in the gather — including the chunk itself, just written — is
    masked; the chunk attends to itself causally through the fresh
    k/v instead).

    ONE softmax spans [prefix | chunk]: after the head-scatter/
    seq-gather all-to-all each rank holds the FULL chunk for its head
    subset, so row i's global chunk position IS i and a plain
    [prefix-mask | tril] concatenated bias is exact — no online-softmax
    merge needed on this path."""
    b, c, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def seq_to_heads(x):
        # [b, L/P, h, d] -> [b, L, h/P, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    L, maxT = qh.shape[1], k_pref.shape[1]
    logits_p = jnp.einsum("bqhd,bkhd->bhqk", qh, k_pref,
                          preferred_element_type=jnp.float32) * scale
    live_p = (jnp.arange(maxT) < prefix_len)[None, None, None, :]
    logits_p = jnp.where(live_p, logits_p, NEG_INF)
    logits_c = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                          preferred_element_type=jnp.float32) * scale
    causal = jnp.tril(jnp.ones((L, L), bool))[None, None]
    logits_c = jnp.where(causal, logits_c, NEG_INF)
    logits = jnp.concatenate([logits_p, logits_c], axis=-1)
    m = logits.max(axis=-1, keepdims=True)
    w = jnp.exp(logits - m)
    # every row keeps at least its causal diagonal, so the sum is > 0
    # even for padding rows past n_valid (their output is garbage the
    # boundary-row slice discards)
    w = (w / w.sum(axis=-1, keepdims=True)).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", w[..., :maxT], v_pref) + \
        jnp.einsum("bhqk,bkhd->bqhd", w[..., maxT:], vh)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_prefill_attention(q, k, v, k_pref, v_pref, prefix_len, mesh, *,
                              axis="sequence", scale=None):
    """Sequence-parallel prefill chunk attention against a paged prefix.

    q/k/v [b, L, h, d] are the chunk (L shards over ``axis``);
    k_pref/v_pref [b, maxT, h, d] the full paged-pool gather.  The
    prefix enters head-sharded over ``(model, sequence)``: with
    ``h_sub = h / (model_size * seq_size)``, the all-to-all hands rank
    (m, j) head block ``m*P + j`` — exactly the ``(model, sequence)``
    partition of the head dim, so no per-rank slicing is needed and
    GSPMD reshards the (replicated) gather with a local slice, not a
    collective."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    spec = _bhd_spec(mesh, q.shape, axis)
    model_ax = spec[2]
    local_heads = q.shape[2] // (mesh.shape[model_ax] if model_ax else 1)
    assert local_heads % n == 0, \
        (f"heads per model shard ({local_heads}) must divide the "
         f"sequence axis size ({n}) for the Ulysses all-to-all — "
         "resolve_sequence_plan routes this case to ring")
    head_axes = (model_ax, axis) if model_ax is not None else axis
    pspec = P(spec[0], None, head_axes, None)
    fn = functools.partial(ulysses_prefill_attention_local,
                           axis_name=axis, scale=scale)
    sharded = shard_map(fn, mesh=mesh,
                        in_specs=(spec, spec, spec, pspec, pspec, P()),
                        out_specs=spec)
    return sharded(q, k, v, k_pref, v_pref, prefix_len)


def ulysses_attention_sharded(q, k, v, mesh, *, axis="sequence", causal=True,
                              attn_fn=None):
    """Global entry: q/k/v [b, L, h, d]; shards L over `axis`, swaps to
    heads for compute (DistributedAttention in deepspeed/sequence/layer.py
    of later snapshots)."""
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    assert q.shape[2] % n == 0, \
        f"num_heads {q.shape[2]} must divide sequence axis size {n}"
    from deepspeed_tpu.ops.attention.ring import _bhd_spec
    spec = _bhd_spec(mesh, q.shape, axis)
    if spec[2] is not None:
        # heads already model-sharded: the per-shard head count must still
        # divide the sequence axis for the all-to-all swap
        assert (q.shape[2] // mesh.shape["model"]) % n == 0, \
            "heads per model shard must divide the sequence axis size"
    fn = functools.partial(ulysses_attention_local, axis_name=axis,
                           causal=causal, attn_fn=attn_fn)
    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return sharded(q, k, v)
