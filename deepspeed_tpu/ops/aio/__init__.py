"""Async file-IO handle for the NVMe swap tier (ZeRO-Infinity).

Reference: the ``aio_handle`` built by ``op_builder/async_io.py`` from
``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp:1`` — sync/async pread/pwrite
with a thread pool, queue depth and block size. Same handle API here, over
``csrc/aio.cpp`` (pthread pool + positional IO) via ctypes.
"""

import ctypes

import numpy as np

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, OpBuilderError

_lib = None
_lib_tried = False


def _native():
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        b = AsyncIOBuilder()
        if b.is_compatible():
            try:
                _lib = b.load()
            except OpBuilderError:
                _lib = None
    return _lib


class AioHandle:
    """Thread-pooled positional file IO over numpy buffers.

    Methods mirror the reference handle: async submissions + wait(), and
    sync convenience wrappers. Falls back to synchronous numpy IO when the
    native lib is unavailable (so tests run anywhere).
    """

    def __init__(self, block_size=1 << 20, queue_depth=4, single_submit=False,
                 overlap_events=True, thread_count=None, o_direct=False):
        self.block_size = block_size
        self.queue_depth = queue_depth
        # the native pool's parallelism knob is its worker-thread count;
        # queue_depth (the reference's per-thread kernel-AIO depth) has no
        # separate meaning in the pthread design and serves as the pool
        # size fallback when thread_count is not given
        self.thread_count = thread_count if thread_count is not None \
            else queue_depth
        lib = _native()
        self._lib = lib
        self._h = lib.ds_aio_new(block_size, self.thread_count,
                                 int(o_direct)) if lib else None
        self._fallback_pending = []
        self._inflight = []      # keep submitted buffers alive until wait()

    def async_pread(self, buf, path, offset=0):
        # reads land in the caller's buffer: it must already be contiguous
        # (a copy here would silently drop the data)
        assert buf.flags["C_CONTIGUOUS"], "read buffer must be contiguous"
        if self._h:
            self._inflight.append(buf)
            self._lib.ds_aio_submit_read(
                self._h, str(path).encode(), buf.ctypes.data,
                buf.nbytes, offset)
        else:
            self._fallback_pending.append(("r", buf, str(path), offset))
        return buf

    def async_pwrite(self, buf, path, offset=0):
        buf = np.ascontiguousarray(buf)
        if self._h:
            self._inflight.append(buf)
            self._lib.ds_aio_submit_write(
                self._h, str(path).encode(), buf.ctypes.data,
                buf.nbytes, offset)
        else:
            self._fallback_pending.append(("w", buf, str(path), offset))
        return buf

    def wait(self):
        if self._h:
            errs = self._lib.ds_aio_wait(self._h)
            self._inflight.clear()
            if errs:
                raise IOError(f"aio: {errs} request(s) failed")
            return 0
        for op, buf, path, offset in self._fallback_pending:
            if op == "w":
                with open(path, "r+b" if offset else "wb") as f:
                    f.seek(offset)
                    f.write(buf.tobytes())
            else:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(buf.nbytes)
                buf[...] = np.frombuffer(data, buf.dtype).reshape(buf.shape)
        self._fallback_pending.clear()
        return 0

    def sync_pread(self, buf, path, offset=0):
        self.async_pread(buf, path, offset)
        self.wait()
        return buf

    def sync_pwrite(self, buf, path, offset=0):
        self.async_pwrite(buf, path, offset)
        self.wait()
        return buf

    def __del__(self):
        h, self._h = self._h, None
        if h and self._lib:
            self._lib.ds_aio_free(h)
