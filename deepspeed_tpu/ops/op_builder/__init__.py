"""Native-op build system: compile C++ host ops on first use, cache by
source hash, bind via ctypes.

Reference: ``op_builder/builder.py:99,438`` (OpBuilder.is_compatible/load,
jit_load) and the registry ``op_builder/all_ops.py:33``. The reference JIT
builds torch CUDA extensions with ninja; here the native surface is
host-side C++ (host optimizer for ZeRO-Offload, async file IO for
ZeRO-Infinity — the TPU compute path is Pallas/XLA, not custom device
code), compiled with g++ into a shared object under ``~/.cache`` and bound
with ctypes so no pybind11 is needed.
"""

import ctypes
import hashlib
import os
import subprocess
import tempfile

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "..", "csrc")
_CACHE = os.environ.get(
    "DEEPSPEED_TPU_OP_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilderError(RuntimeError):
    pass


class OpBuilder:
    """Compile-and-load for one C++ translation unit.

    Same contract as the reference builder: ``is_compatible()`` answers
    cheaply without building, ``load()`` returns the bound module (here a
    ctypes.CDLL) building it if needed.
    """

    NAME = None          # registry key (e.g. "cpu_adam")
    SOURCE = None        # file under csrc/
    EXTRA_FLAGS = ()

    _loaded = {}

    def source_path(self):
        return os.path.abspath(os.path.join(_CSRC, self.SOURCE))

    def compiler(self):
        return os.environ.get("CXX", "g++")

    def is_compatible(self, verbose=False):
        if not os.path.exists(self.source_path()):
            return False
        try:
            subprocess.run([self.compiler(), "--version"], capture_output=True,
                           check=True)
            return True
        except (OSError, subprocess.CalledProcessError):
            return False

    def base_flags(self):
        flags = ["-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp"]
        # AVX2 is the reference's SIMD floor (csrc/includes/simd.h); fall
        # back transparently if the toolchain refuses the flag.
        if self._flag_ok("-mavx2"):
            flags.append("-mavx2")
        return flags + list(self.EXTRA_FLAGS)

    def _flag_ok(self, flag):
        with tempfile.NamedTemporaryFile("w", suffix=".cpp") as f:
            f.write("int main(){return 0;}")
            f.flush()
            r = subprocess.run(
                [self.compiler(), flag, f.name, "-o", os.devnull],
                capture_output=True)
            return r.returncode == 0

    def _so_path(self):
        with open(self.source_path(), "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        return os.path.join(_CACHE, f"{self.NAME}_{tag}.so")

    def load(self, verbose=False):
        if self.NAME in OpBuilder._loaded:
            return OpBuilder._loaded[self.NAME]
        so = self._so_path()
        if not os.path.exists(so):
            os.makedirs(_CACHE, exist_ok=True)
            cmd = [self.compiler(), *self.base_flags(),
                   self.source_path(), "-o", so + ".tmp"]
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode != 0:
                raise OpBuilderError(
                    f"building {self.NAME} failed:\n{' '.join(cmd)}\n{r.stderr}")
            os.replace(so + ".tmp", so)
        lib = ctypes.CDLL(so)
        self._annotate(lib)
        OpBuilder._loaded[self.NAME] = lib
        return lib

    def _annotate(self, lib):
        """Set argtypes/restype for type safety; subclasses override."""


_i64 = ctypes.c_int64
_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    SOURCE = "host_adam.cpp"

    def _annotate(self, lib):
        lib.ds_adam_step.argtypes = [
            _f32p, _f32p, _f32p, _f32p, _i64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, _u16p]
        lib.ds_adagrad_step.argtypes = [
            _f32p, _f32p, _f32p, _i64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float,
            _u16p]
        lib.ds_l2_norm_sq.argtypes = [_f32p, _i64]
        lib.ds_l2_norm_sq.restype = ctypes.c_double
        lib.ds_has_inf_nan.argtypes = [_f32p, _i64]
        lib.ds_has_inf_nan.restype = ctypes.c_int
        lib.ds_axpy.argtypes = [_f32p, _f32p, _i64]
        lib.ds_scale.argtypes = [_f32p, _i64, ctypes.c_float]
        lib.ds_f32_to_bf16.argtypes = [_f32p, _u16p, _i64]
        lib.ds_bf16_to_f32.argtypes = [_u16p, _f32p, _i64]


class CPUAdagradBuilder(CPUAdamBuilder):
    """Adagrad shares the translation unit (reference keeps separate
    csrc/adagrad; one TU serves both here) — and therefore the .so."""
    NAME = "cpu_adagrad"

    def _so_path(self):
        return CPUAdamBuilder()._so_path()


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"
    SOURCE = "aio.cpp"
    EXTRA_FLAGS = ("-pthread",)

    def _annotate(self, lib):
        lib.ds_aio_new.argtypes = [_i64, ctypes.c_int, ctypes.c_int]
        lib.ds_aio_new.restype = ctypes.c_void_p
        lib.ds_aio_submit_read.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, _i64, _i64]
        lib.ds_aio_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, _i64, _i64]
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_free.argtypes = [ctypes.c_void_p]


ALL_OPS = {b.NAME: b for b in (CPUAdamBuilder(), CPUAdagradBuilder(),
                               AsyncIOBuilder())}


def op_report():
    """[(name, compatible, installed)] for ds_report (reference
    deepspeed/env_report.py)."""
    rows = []
    for name, b in ALL_OPS.items():
        rows.append((name, b.is_compatible(), os.path.exists(b._so_path())
                     if b.is_compatible() else False))
    return rows
