"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``:
Triton SDD/DSD/DDS matmuls + sparse softmax + SparsityConfig patterns;
here the patterns drive the Pallas flash kernel's block-skip predicate).
"""

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
    VariableSparsityConfig)


class SparseSelfAttention:
    """Functional counterpart of the reference ``SparseSelfAttention``
    module (sparse_self_attention.py): q/k/v [b, l, h, d] -> context, with
    the pattern from `sparsity_config`."""

    def __init__(self, sparsity_config, attn_mask_mode="add", scale=None):
        self.sparsity_config = sparsity_config
        self.attn_mask_mode = attn_mask_mode
        self.scale = scale

    def __call__(self, q, k, v, causal=None):
        from deepspeed_tpu.ops.attention.flash import flash_attention
        if causal is None:
            causal = self.sparsity_config.__dict__.get(
                "attention", "bidirectional") == "unidirectional"
        return flash_attention(q, k, v, causal=causal, scale=self.scale,
                               sparsity_config=self.sparsity_config)


def layout_to_bias(layout, seq_len, block, dtype=jnp.float32):
    """Dense additive bias from a block layout (the jnp oracle used by
    tests): [H, n, n] blocks -> [1, H, L, L] with -inf on inactive."""
    import numpy as np
    H, nq, nk = layout.shape
    mask = np.repeat(np.repeat(np.asarray(layout), block, 1), block, 2)
    bias = np.where(mask > 0, 0.0, float(np.finfo(np.float32).min))
    return jnp.asarray(bias[None], dtype)
