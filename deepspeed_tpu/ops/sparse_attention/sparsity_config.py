"""Sparsity pattern configs -> block layouts.

Reference: ``deepspeed/ops/sparse_attention/sparsity_config.py`` —
``SparsityConfig`` :10, ``Fixed`` :95, ``Variable`` :239, ``BigBird``
:411, ``BSLongformer``, ``LocalSlidingWindow``; consumed there by Triton
block-sparse matmuls, here by the Pallas flash kernel's block-skip
predicate (ops/attention/flash.py `layout=`).

A layout is an int32 array ``[layout_heads, num_blocks, num_blocks]``
(1 = attend). ``block`` is the block granularity — the flash kernel runs
with block_q = block_k = block, so a 0 block is skipped entirely; that
is where the sparse speedup comes from (reference claim: 10x longer
sequences, ~6x faster, BASELINE.md sparse row).
"""

import numpy as np


class SparsityConfig:
    """Base: dense layout; subclasses carve structure out of it."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    @property
    def layout_heads(self):
        return self.num_heads if self.different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by "
                             f"block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.layout_heads, n, n), np.int32)

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks active (reference ``DenseSparsityConfig``)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (reference :95 / the original Sparse Transformer):
    rows are grouped into non-overlapping local windows of
    ``num_local_blocks``; each row attends within its window, and the
    last ``num_global_blocks`` columns of every window are global —
    attended by everyone (and, with ``horizontal_global_attention``,
    attending to everyone)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        assert attention in ("unidirectional", "bidirectional")
        assert num_global_blocks <= num_local_blocks
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns > 1 needs "
                             "different_layout_per_head=True")
        assert num_local_blocks % num_global_blocks == 0
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns "
                f"({num_different_global_patterns}) cannot exceed "
                f"num_local_blocks/num_global_blocks "
                f"({num_local_blocks // num_global_blocks}): the rotated "
                "global slice would leave the window (reference asserts "
                "the same bound)")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(layout.shape[0]):
            # local windows
            for w0 in range(0, n, L):
                w1 = min(w0 + L, n)
                layout[h, w0:w1, w0:w1] = 1
            # global columns: the pattern can differ per head (reference
            # num_different_global_patterns rotates which sub-slice of
            # the window is global)
            pat = h % self.num_different_global_patterns
            for w0 in range(0, n, L):
                g1 = min(w0 + L, n) - pat * G
                g0 = max(g1 - G, 0)
                layout[h, :, g0:g1] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Variable pattern (reference :239): custom local window sizes,
    explicit global block index ranges, plus random blocks."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        assert attention in ("unidirectional", "bidirectional")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None:
            assert len(global_block_end_indices) == \
                len(self.global_block_indices)
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = np.random.default_rng(self.seed)
        for h in range(layout.shape[0]):
            # local windows of varying width; the last width repeats
            w0 = 0
            i = 0
            while w0 < n:
                w = self.local_window_blocks[
                    min(i, len(self.local_window_blocks) - 1)]
                w1 = min(w0 + w, n)
                layout[h, w0:w1, w0:w1] = 1
                w0, i = w1, i + 1
            # globals
            for j, g0 in enumerate(self.global_block_indices):
                if g0 >= n:
                    continue
                g1 = g0 + 1 if self.global_block_end_indices is None \
                    else min(self.global_block_end_indices[j], n)
                layout[h, :, g0:g1] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1
            # random blocks: unidirectional rows sample from their own
            # causal range so tril doesn't silently drop them
            for r in range(self.num_random_blocks):
                for q in range(n):
                    hi = q + 1 if self.attention == "unidirectional" else n
                    layout[h, q, rng.integers(0, hi)] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference :411): sliding window + random blocks + global
    first/last blocks."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        assert attention in ("unidirectional", "bidirectional")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        W, G = self.num_sliding_window_blocks, self.num_global_blocks
        rng = np.random.default_rng(self.seed)
        half = W // 2
        for h in range(layout.shape[0]):
            for q in range(n):
                lo, hi = max(0, q - half), min(n, q + half + 1)
                layout[h, q, lo:hi] = 1
            layout[h, :, :G] = 1       # global: first blocks as columns
            layout[h, :G, :] = 1       # ...and as rows
            layout[h, :, n - G:] = 1
            layout[h, n - G:, :] = 1
            for q in range(n):
                for r in range(self.num_random_blocks):
                    hi = q + 1 if self.attention == "unidirectional" else n
                    layout[h, q, rng.integers(0, hi)] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (reference): sliding window + explicit
    global block indices (rows and columns)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        half = self.num_sliding_window_blocks // 2
        for h in range(layout.shape[0]):
            for q in range(n):
                lo, hi = max(0, q - half), min(n, q + half + 1)
                layout[h, q, lo:hi] = 1
            for j, g0 in enumerate(self.global_block_indices):
                if g0 >= n:
                    continue
                g1 = g0 + 1 if self.global_block_end_indices is None \
                    else min(self.global_block_end_indices[j], n)
                layout[h, :, g0:g1] = 1
                layout[h, g0:g1, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference ``LocalSlidingWindowSparsityConfig``)."""

    def __init__(self, num_heads, block=128, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        half = self.num_sliding_window_blocks // 2
        for q in range(n):
            if self.attention == "unidirectional":
                lo = max(0, q - self.num_sliding_window_blocks + 1)
                layout[0, q, lo:q + 1] = 1
            else:
                lo, hi = max(0, q - half), min(n, q + half + 1)
                layout[0, q, lo:hi] = 1
        return layout
