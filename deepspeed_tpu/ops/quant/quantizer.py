"""Symmetric group quantization for weight-only int8/int4 serving.

Reference: the group-quantization CUDA kernels
(``csrc/quantization/quantize.cu``, ``dequantize.cu``,
``pt_binding.cpp:1``) behind ``GroupQuantizer``
(``module_inject/replace_module.py:138``) and the MoQ path. The TPU
version is pure jax (XLA fuses the dequant convert+multiply into the
consuming matmul) plus a Pallas dequant-matmul kernel (kernels.py) for
the serving hot path.

Weights quantize per group along the contraction (input) axis: a kernel
[in, out] with group size G stores q int8 [in, out] and scales
[ceil(in/G), out] — each group of (up to) G input rows shares one scale
per output column.  A non-divisible ``in`` gets a short TRAILING group
(its scale covers only the real rows — zero padding never inflates an
absmax, and the padded rows are sliced away before they exist in the
stored q).  Symmetric: q = round(x / s), s = max|x| / qmax.
"""

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Quantized weight leaf: (q int8, scale) with the original dtype.
    Lives inside a params pytree; jit/flatten treat q and scale as
    children so the tree passes straight into jitted functions.
    ``group_size`` is part of the aux data: with a trailing partial
    group the grouping is NOT derivable from the shapes alone
    (ceil(in/groups) != the real group size), so dequantization must
    carry it."""

    def __init__(self, q, scale, dtype=jnp.bfloat16, bits=8,
                 group_size=None):
        self.q = q
        self.scale = scale
        self.dtype = dtype
        self.bits = bits
        self.group_size = group_size

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        """True storage footprint: the int8 payload AND the scale rows.
        Counting only q under-reports by scale.size * 4 bytes — at small
        group sizes the scales are a double-digit percentage of the
        whole tensor, and the serving byte ledgers (health / mem
        telemetry) bill real bytes, not wishful ones."""
        return int(self.q.size) * self.q.dtype.itemsize + \
            int(self.scale.size) * self.scale.dtype.itemsize

    def dequant(self):
        return dequantize(self.q, self.scale, self.dtype,
                          group_size=self.group_size)

    def tree_flatten(self):
        return (self.q, self.scale), (self.dtype, self.bits,
                                      self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def __repr__(self):
        return (f"QTensor(shape={tuple(jnp.shape(self.q))}, "
                f"bits={self.bits})")


def quantize(x, *, bits=8, group_size=128):
    """[in, out] float -> (q int8 [in, out], scale f32 [ceil(in/G), out]).
    A non-divisible ``in`` quantizes with a short trailing group (the
    zero padding used for the reshape cannot raise any |x| max, and the
    padded rows are sliced off the returned q)."""
    assert bits in (8, 4), f"bits={bits} (int8 / int4 symmetric)"
    n_in, n_out = x.shape
    qmax = 2.0 ** (bits - 1) - 1
    groups = -(-n_in // group_size)
    pad = groups * group_size - n_in
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
    g = xf.reshape(groups, group_size, n_out)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)      # [G, 1, out]
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
    q = q.reshape(groups * group_size, n_out)
    if pad:
        q = q[:n_in]
    return q, scale[:, 0, :]


def dequantize(q, scale, dtype=jnp.bfloat16, group_size=None):
    """Inverse of :func:`quantize`.  ``group_size`` is required when the
    quantization used a trailing partial group (in % G != 0): the
    grouping is not derivable from the shapes then.  Omitted, it falls
    back to the exact-divisible inference (in // groups) and raises on
    ambiguity rather than silently mis-grouping."""
    n_in, n_out = q.shape
    groups = scale.shape[0]
    if group_size is None:
        if n_in % groups != 0:
            raise ValueError(
                f"dequantize: {groups} scale rows do not evenly divide "
                f"{n_in} input rows — this tensor was quantized with a "
                "trailing partial group; pass group_size=")
        group_size = n_in // groups
    pad = groups * group_size - n_in
    if pad < 0 or pad >= group_size:
        raise ValueError(
            f"dequantize: group_size={group_size} inconsistent with "
            f"q rows {n_in} / {groups} scale rows")
    qf = q.astype(jnp.float32)
    if pad:
        qf = jnp.pad(qf, ((0, pad), (0, 0)))
    g = qf.reshape(groups, group_size, n_out)
    out = (g * scale[:, None, :]).reshape(groups * group_size, n_out)
    if pad:
        out = out[:n_in]
    return out.astype(dtype)


def _eligible(leaf):
    """2-D floating kernels quantize; the contraction dim need NOT
    divide by the group size any more (a trailing partial group handles
    the remainder — eligibility is shape-only now), but degenerate
    single-row kernels stay float — one scale per element saves
    nothing."""
    shape = jnp.shape(leaf)
    return (len(shape) == 2 and shape[0] > 1 and
            jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating))


def quantize_tree(params, *, bits=8, group_size=128, predicate=None):
    """Quantize every eligible 2-D kernel in a param tree; other leaves
    pass through. Returns a tree with QTensor leaves (the reference's
    GroupQuantizer sweep over injected containers)."""
    pred = predicate or (lambda path, leaf: True)

    def per_leaf(path, leaf):
        if _eligible(leaf) and pred(path, leaf):
            dtype = jnp.asarray(leaf).dtype
            q, s = quantize(jnp.asarray(leaf), bits=bits,
                            group_size=group_size)
            return QTensor(q, s, dtype, bits, group_size)
        return leaf

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [per_leaf(jax.tree_util.keystr(p), l) for p, l in flat])


def dequantize_tree(params):
    """Materialize QTensor leaves back to floats (used inside jit: XLA
    schedules each dequant next to its consumer, so peak memory stays
    int8-tree + one layer's floats, not a full float copy)."""
    return jax.tree.map(
        lambda l: l.dequant() if isinstance(l, QTensor) else l,
        params, is_leaf=lambda l: isinstance(l, QTensor))
