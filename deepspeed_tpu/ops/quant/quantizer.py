"""Symmetric group quantization for weight-only int8/int4 serving.

Reference: the group-quantization CUDA kernels
(``csrc/quantization/quantize.cu``, ``dequantize.cu``,
``pt_binding.cpp:1``) behind ``GroupQuantizer``
(``module_inject/replace_module.py:138``) and the MoQ path. The TPU
version is pure jax (XLA fuses the dequant convert+multiply into the
consuming matmul) plus a Pallas dequant-matmul kernel (kernels.py) for
the serving hot path.

Weights quantize per group along the contraction (input) axis: a kernel
[in, out] with group size G stores q int8 [in, out] and scales
[in/G, out] — each group of G input rows shares one scale per output
column. Symmetric: q = round(x / s), s = max|x| / qmax.
"""

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Quantized weight leaf: (q int8, scale) with the original dtype.
    Lives inside a params pytree; jit/flatten treat q and scale as
    children so the tree passes straight into jitted functions."""

    def __init__(self, q, scale, dtype=jnp.bfloat16, bits=8):
        self.q = q
        self.scale = scale
        self.dtype = dtype
        self.bits = bits

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.size * self.q.dtype.itemsize + \
            self.scale.size * self.scale.dtype.itemsize

    def dequant(self):
        return dequantize(self.q, self.scale, self.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.dtype, self.bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def __repr__(self):
        return (f"QTensor(shape={tuple(jnp.shape(self.q))}, "
                f"bits={self.bits})")


def quantize(x, *, bits=8, group_size=128):
    """[in, out] float -> (q int8 [in, out], scale f32 [in/G, out]).
    `in` must divide by group_size (callers pick eligible leaves)."""
    assert bits in (8, 4), f"bits={bits} (int8 / int4 symmetric)"
    n_in, n_out = x.shape
    assert n_in % group_size == 0, (n_in, group_size)
    qmax = 2.0 ** (bits - 1) - 1
    g = x.reshape(n_in // group_size, group_size, n_out).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)      # [G, 1, out]
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    q = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(n_in, n_out), scale[:, 0, :]


def dequantize(q, scale, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize`."""
    n_in, n_out = q.shape
    groups = scale.shape[0]
    g = q.reshape(groups, n_in // groups, n_out).astype(jnp.float32)
    return (g * scale[:, None, :]).reshape(n_in, n_out).astype(dtype)


def _eligible(leaf, group_size):
    shape = jnp.shape(leaf)
    return (len(shape) == 2 and shape[0] % group_size == 0 and
            shape[0] >= group_size and
            jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating))


def quantize_tree(params, *, bits=8, group_size=128, predicate=None):
    """Quantize every eligible 2-D kernel in a param tree; other leaves
    pass through. Returns a tree with QTensor leaves (the reference's
    GroupQuantizer sweep over injected containers)."""
    pred = predicate or (lambda path, leaf: True)

    def per_leaf(path, leaf):
        if _eligible(leaf, group_size) and pred(path, leaf):
            dtype = jnp.asarray(leaf).dtype
            q, s = quantize(jnp.asarray(leaf), bits=bits,
                            group_size=group_size)
            return QTensor(q, s, dtype, bits)
        return leaf

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [per_leaf(jax.tree_util.keystr(p), l) for p, l in flat])


def dequantize_tree(params):
    """Materialize QTensor leaves back to floats (used inside jit: XLA
    schedules each dequant next to its consumer, so peak memory stays
    int8-tree + one layer's floats, not a full float copy)."""
    return jax.tree.map(
        lambda l: l.dequant() if isinstance(l, QTensor) else l,
        params, is_leaf=lambda l: isinstance(l, QTensor))
