"""Quantization ops (reference ``csrc/quantization/`` — quantize.cu,
dequantize.cu, pt_binding.cpp — and ``deepspeed/ops/quantizer/``)."""

from deepspeed_tpu.ops.quant.quantizer import (  # noqa: F401
    QTensor, dequantize, dequantize_tree, quantize, quantize_tree)
from deepspeed_tpu.ops.quant.kernels import int8_matmul  # noqa: F401
