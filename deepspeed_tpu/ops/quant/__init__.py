"""Quantization ops (reference ``csrc/quantization/`` — quantize.cu,
dequantize.cu, pt_binding.cpp — and ``deepspeed/ops/quantizer/``)."""

from deepspeed_tpu.ops.quant.quantizer import (  # noqa: F401
    QTensor, dequantize, dequantize_tree, quantize, quantize_tree)
from deepspeed_tpu.ops.quant.kernels import int8_matmul  # noqa: F401
from deepspeed_tpu.ops.quant.kv import (  # noqa: F401
    KV_QUANT_DTYPES, dequantize_kv_rows, is_quantized_kv, kv_dtype_name,
    kv_page_bytes, paged_gather, paged_pool_layer, paged_write,
    quantize_kv_rows)
