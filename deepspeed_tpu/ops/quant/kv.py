"""Quantized paged KV-cache storage (int8 / fp8 pages + scale pools).

The serving decode path is bound twice by KV bytes: HBM *capacity* caps
concurrent slots and prefix-cache depth (page arithmetic — the currency
every scheduler mechanism spends), and HBM *bandwidth* bounds the
per-token attention gather.  Quantizing the page pools attacks both at
once — the PagedAttention + KV-quantization direction (vLLM; KIVI /
FP8-KV): an fp32 KV token row of ``head_dim`` floats becomes
``head_dim`` int8 (or fp8-e4m3) values plus ONE fp32 scale, a ~3.8x
byte reduction at head_dim 64 (2x vs bf16).

Storage contract
----------------
A quantized pool layer holds FOUR leaves instead of two::

    k_pages  [num_pages, page_size, kv_heads, head_dim]  int8 | fp8
    v_pages  [num_pages, page_size, kv_heads, head_dim]  int8 | fp8
    k_scale  [num_pages, page_size, kv_heads, 1]         float32
    v_scale  [num_pages, page_size, kv_heads, 1]         float32

The scale pools are a PARALLEL POOL indexed by the same page ids as the
payload pools — a scale row travels with its page through every host
mechanism (COW ``copy_page``, donation, ``truncate_slot``, handoff
``adopt_chain``) for free, because those mechanisms move page *ids*,
never bytes.  Scales are therefore part of the page's identity: a
prefix-cache hit shares payload and scales as one unit, and the byte
ledgers (``pool_bytes_per_device``, mem telemetry, health) count them
simply by summing leaves.  Keeping the scale leaves rank-4 (trailing
dim 1) matters: the pool axis family's single NamedSharding
(``P(pages, None, kv_heads, None)``) broadcasts over all four leaves,
so the scales shard their kv-head dim over ``model`` exactly like the
payload they describe.

Quantization granularity is per token-row per kv-head (one scale per
written KV vector).  Coarser per-page scales would need requantization
on every append — pages fill token by token — which compounds error;
per-row scales quantize each vector exactly once, at write time, and
never touch it again.

Numerics: symmetric absmax.  ``scale = max|x| / qmax`` (qmax 127 for
int8, 448 for fp8-e4m3), ``q = cast(x / scale)`` (round+clip for int8,
dtype cast for fp8), ``dequant = q * scale``.  All scale math in fp32.
"""

import jax
import jax.numpy as jnp

__all__ = ["KV_QUANT_DTYPES", "is_quantized_kv", "kv_dtype_name",
           "kv_storage_dtype", "kv_qmax", "quantize_kv_rows",
           "dequantize_kv_rows", "paged_pool_layer", "paged_write",
           "paged_gather", "kv_page_bytes", "fp8_supported"]

# accepted quantized kv_dtype spellings (the float spellings live in
# inference.engine.DTYPES); "fp8" is e4m3 — the inference-standard
# format (e5m2's 2-bit mantissa is a gradients format)
KV_QUANT_DTYPES = ("int8", "fp8")

_QMAX = {"int8": 127.0, "fp8": 448.0}


def fp8_supported():
    """True when this jax runtime ships float8_e4m3fn."""
    return hasattr(jnp, "float8_e4m3fn")


def is_quantized_kv(dtype):
    """True for the string names of quantized KV dtypes ("int8"/"fp8");
    jnp dtypes and float names are the classic float pool path."""
    return isinstance(dtype, str) and dtype in KV_QUANT_DTYPES


def kv_qmax(name):
    return _QMAX[name]


def kv_storage_dtype(name):
    """Storage dtype for a quantized KV pool, validating runtime
    support (fp8 needs a jax build with float8_e4m3fn)."""
    if name == "int8":
        return jnp.int8
    if name == "fp8":
        if not fp8_supported():
            raise ValueError(
                "kv_dtype='fp8' needs a jax runtime with "
                "float8_e4m3fn; this build has none — use 'int8'")
        return jnp.float8_e4m3fn
    raise ValueError(f"unknown quantized kv dtype {name!r}; "
                     f"expected one of {KV_QUANT_DTYPES}")


def kv_dtype_name(layer):
    """Canonical kv-dtype name of one pool layer dict (the live truth —
    health() reports what is allocated, not what was configured)."""
    dt = layer["k_pages"].dtype
    if "k_scale" in layer:
        return "int8" if dt == jnp.int8 else "fp8"
    return jnp.dtype(dt).name


def quantize_kv_rows(x, name):
    """Per-row symmetric quantization of KV vectors: ``x [..., d]`` ->
    ``(q [..., d] storage-dtype, scale [..., 1] f32)``.  The trailing
    scale dim keeps the result rank-aligned with the rank-4 scale pool
    (one broadcastable multiply dequantizes)."""
    qmax = _QMAX[name]
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0)
    y = xf / scale
    if name == "int8":
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        q = y.astype(kv_storage_dtype(name))
    return q, scale


def dequantize_kv_rows(q, scale, dtype):
    """``q [..., d] * scale [..., 1]`` -> ``[..., d]`` in ``dtype``."""
    return (q.astype(jnp.float32) * scale.astype(jnp.float32)) \
        .astype(dtype)


def paged_pool_layer(num_pages, page_size, kv_heads, head_dim, dtype):
    """One layer's pool leaves: two float pools classically, four
    leaves (int8/fp8 payload + f32 scale pools) when ``dtype`` is a
    quantized kv-dtype name."""
    if is_quantized_kv(dtype):
        st = kv_storage_dtype(dtype)
        return {
            "k_pages": jnp.zeros((num_pages, page_size, kv_heads,
                                  head_dim), st),
            "v_pages": jnp.zeros((num_pages, page_size, kv_heads,
                                  head_dim), st),
            "k_scale": jnp.zeros((num_pages, page_size, kv_heads, 1),
                                 jnp.float32),
            "v_scale": jnp.zeros((num_pages, page_size, kv_heads, 1),
                                 jnp.float32),
        }
    return {
        "k_pages": jnp.zeros((num_pages, page_size, kv_heads, head_dim),
                             dtype),
        "v_pages": jnp.zeros((num_pages, page_size, kv_heads, head_dim),
                             dtype),
    }


def _qname(storage_dtype):
    return "int8" if storage_dtype == jnp.int8 else "fp8"


def paged_write(layer, page_ids, offsets, k_new, v_new):
    """Write K/V rows through the page table, quantizing iff the layer
    carries scale pools.  ``page_ids``/``offsets`` have any index shape
    X; ``k_new``/``v_new`` are ``X + (kv_heads, head_dim)``.  Returns
    the updated pool-leaf dict (same key set as ``layer``'s pool
    leaves).  Out-of-range page ids drop the write (``mode="drop"``) —
    the masking contract every paged branch already relies on — and the
    scale write uses the SAME masked ids, so payload and scale stay
    atomic per row.  The float path is byte-identical to the
    pre-quantization code (zero-cost-when-off: the branch is a
    trace-time dict-key check)."""
    k_pages, v_pages = layer["k_pages"], layer["v_pages"]
    if "k_scale" not in layer:
        return {
            "k_pages": k_pages.at[page_ids, offsets].set(
                k_new.astype(k_pages.dtype), mode="drop"),
            "v_pages": v_pages.at[page_ids, offsets].set(
                v_new.astype(v_pages.dtype), mode="drop"),
        }
    name = _qname(k_pages.dtype)
    kq, ks = quantize_kv_rows(k_new, name)
    vq, vs = quantize_kv_rows(v_new, name)
    return {
        "k_pages": k_pages.at[page_ids, offsets].set(kq, mode="drop"),
        "v_pages": v_pages.at[page_ids, offsets].set(vq, mode="drop"),
        "k_scale": layer["k_scale"].at[page_ids, offsets].set(
            ks, mode="drop"),
        "v_scale": layer["v_scale"].at[page_ids, offsets].set(
            vs, mode="drop"),
    }


def paged_gather(pools, page_table, dtype):
    """Gather per-slot contiguous K/V buffers through the page table,
    dequantizing when the pools are quantized: returns ``(k, v)`` of
    shape ``[slots, max_pages * page_size, kv_heads, head_dim]``.  The
    float path returns the raw gathered pages (exactly the
    pre-quantization behavior); the quantized path gathers payload AND
    scale pools (the scales ride the same page ids) and dequantizes to
    ``dtype`` — the jnp reference/oracle path, where the transient
    dequantized buffer is the price of GSPMD-partitionable ops.  The
    fast path on any topology is the Pallas kernel in
    ``ops/attention/decode.py``: its quantized variants fetch each
    page's scale block through the same prefetched page-table index
    map and dequantize in VMEM (shard_mapped per-shard on a
    multi-device mesh), so only quantized bytes stream from HBM."""
    from deepspeed_tpu.ops.attention.decode import gather_pages
    k = gather_pages(pools["k_pages"], page_table)
    v = gather_pages(pools["v_pages"], page_table)
    if "k_scale" in pools:
        ks = gather_pages(pools["k_scale"], page_table)
        vs = gather_pages(pools["v_scale"], page_table)
        k = dequantize_kv_rows(k, ks, dtype)
        v = dequantize_kv_rows(v, vs, dtype)
    return k, v


def kv_page_bytes(num_layers, kv_heads, head_dim, page_size, dtype):
    """Exact bytes one KV page costs across ALL layers (K + V payload
    plus, for quantized dtypes, the f32 scale rows).  This is the
    page-arithmetic unit the capacity ledgers and the autotuner's
    feasibility pruning bill in; it must agree with the allocated
    leaves' ``nbytes`` to the byte (pinned by tests/unit/
    test_kv_quant.py against real device pools)."""
    if is_quantized_kv(dtype):
        per_row = head_dim * jnp.dtype(kv_storage_dtype(dtype)).itemsize \
            + 4                                  # + one f32 scale
    else:
        per_row = head_dim * jnp.dtype(dtype).itemsize
    return 2 * int(num_layers) * int(page_size) * int(kv_heads) * per_row
