"""Pallas TPU int8 dequant-matmul (weight-only quantized serving matmul).

Reference: the int8 GEMM + dequantize path of the inference kernels
(``csrc/transformer/inference/csrc/dequantize.cu``,
``csrc/quantization/pt_binding.cpp``). The weight stays int8 in HBM and
is dequantized tile-by-tile in VMEM right before the MXU contraction, so
HBM traffic is halved vs bf16 weights — the property that matters for
memory-bandwidth-bound decode.

The serving engine reaches the same property through XLA: QTensor leaves
dequantize inside the jitted forward (quantizer.dequantize_tree) and XLA
fuses the int8 convert+scale into the matmul's operand read, so the HBM
stream stays int8 (measured: int8 decode beats bf16 in
benchmarks/inference_bench.py). This kernel is the explicit-control
Pallas equivalent — the oracle-tested building block for custom serving
paths where fusion decisions must not be left to the compiler.

Tiling: grid (m_blocks, n_blocks, k_blocks), k innermost with an fp32
accumulator in VMEM scratch. block_k equals the quantization group size
so each weight tile owns exactly one scale row.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_scr, *, nk):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    x = x_ref[...]                       # [bm, bk]
    w = q_ref[...].astype(jnp.float32) * s_ref[0][None, :]  # [bk, bn] dequant
    acc_scr[:] += jax.lax.dot_general(
        x, w.astype(x.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[...] = acc_scr[:].astype(o_ref.dtype)


def int8_matmul(x, q, scale, *, block_m=None, block_n=256, interpret=None):
    """x [m, k] float @ dequant(q [k, n] int8, scale [k/G, n]) -> [m, n].

    The k block size is the quantization group size G (one scale row per
    weight tile). Oracle: ``x @ dequantize(q, scale)``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    k2, n = q.shape
    groups = scale.shape[0]
    assert k == k2 and k % groups == 0
    block_k = k // groups
    if block_m is None:
        block_m = min(256, m) if m % 8 == 0 or m >= 8 else m
    while m % block_m != 0:
        block_m //= 2
        block_m = max(block_m, 1)
    block_n = min(block_n, n)
    while n % block_n != 0:
        block_n //= 2
    nm, nn, nk = m // block_m, n // block_n, k // block_k

    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, block_n), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[
            pl.ANY if pltpu is None
            else pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=interpret,
    )(x, q, scale.astype(jnp.float32))
    return out
