"""Pallas TPU int8 dequant-matmul (weight-only quantized serving matmul).

Reference: the int8 GEMM + dequantize path of the inference kernels
(``csrc/transformer/inference/csrc/dequantize.cu``,
``csrc/quantization/pt_binding.cpp``). The weight stays int8 in HBM and
is dequantized tile-by-tile in VMEM right before the MXU contraction, so
HBM traffic is halved vs bf16 weights — the property that matters for
memory-bandwidth-bound decode.

XLA does NOT deliver this on its own: a ``x @ dequantize(q, s)`` under
jit materializes the full bf16 weight (measured 2.4x a plain bf16 matmul
at decode shapes on v5e — extra write+read instead of saved bandwidth),
which is exactly the regression VERDICT r3 flagged. This kernel is the
serving decode path: the int8 block streams HBM->VMEM, dequantizes on
the VPU, and feeds the MXU, with the fp32 accumulator in VMEM scratch.

Tiling favors tiny-m decode: the k axis stays whole (one grid step) for
hidden sizes up to ``block_k_budget`` bytes of int8 per n tile, so the
grid is (m_blocks, n_blocks) and Mosaic double-buffers the weight DMA
across n steps; k splits only for very large contractions, in multiples
of the quantization group size so each k step owns whole scale rows.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_scr, *, nk, gpb, group):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    x = x_ref[...]                       # [bm, bk]
    q = q_ref[...]                       # [bk, bn] int8
    # scale arrives pre-reshaped to (nk, gpb, n) so each k step's block
    # (1, gpb, bn) selects whole rows — a dynamic sublane slice inside
    # the kernel would need a multiple-of-8 proof Mosaic can't make
    s = s_ref[0]                         # [gpb, bn] f32
    # Per-group UNSCALED matmuls with the scale applied to the [bm, bn]
    # partial product, not the [bk, bn] weight block: the per-element
    # dequant work drops to a single int8->bf16 convert (the MXU needs
    # the convert regardless), and the scale multiply touches bm*bn*gpb
    # elements instead of bk*bn — at decode m this is ~group x less VPU
    # work, which was the kernel's bottleneck, not HBM.
    acc = acc_scr[...]
    for g in range(gpb):                 # static unroll: gpb is small
        xg = x[:, g * group:(g + 1) * group]
        wg = q[g * group:(g + 1) * group, :].astype(x.dtype)
        part = jax.lax.dot_general(
            xg, wg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc = acc + part * s[g, :][None, :]
    acc_scr[...] = acc

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[...] = acc_scr[:].astype(o_ref.dtype)


def int8_matmul(x, q, scale, *, block_m=None, block_n=None,
                block_k_budget=2 << 20, interpret=None):
    """x [m, k] float @ dequant(q [k, n] int8, scale [k/G, n]) -> [m, n].

    Oracle: ``x @ dequantize(q, scale)``. m is padded to the 8-row
    sublane internally (decode calls come in at m = batch).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = x.shape
    k2, n = q.shape
    groups = scale.shape[0]
    assert k == k2 and k % groups == 0
    group = k // groups

    # sublane-dim blocks must be 8-multiples OR the full axis: a tiny
    # decode m rides through as one full-axis block (no pad/slice ops,
    # which cost more than the matmul at m=1)
    m_pad = m
    if m % 8 and m > 8:
        m_pad = -(-m // 8) * 8
        x = jnp.pad(x, ((0, m_pad - m), (0, 0)))

    if block_m is None:
        block_m = min(256, m_pad)
    while m_pad % block_m != 0:
        block_m //= 2
        block_m = max(block_m, 1)
    if block_n is None:
        # 512 measured best inside a full decode program (multi-step
        # grids keep Mosaic's DMA double-buffering active, which matters
        # more than per-step overhead once other ops surround the call)
        block_n = 512
    # lane-dim blocks must be multiples of 128 (or the whole axis)
    block_n = min(block_n, n)
    if n % block_n or block_n % 128:
        cands = [d for d in range(128, n, 128) if n % d == 0
                 and d <= block_n]
        block_n = max(cands) if cands else n
    # whole-k blocks while the int8 tile fits the budget; otherwise split
    # on group boundaries. A split block_k is the x operand's LANE dim,
    # so it must also be a multiple of 128 (whole-k is always legal).
    gpb = groups
    while gpb > 1 and (gpb * group * block_n > block_k_budget
                       or groups % gpb != 0
                       or (gpb * group) % 128 != 0):
        gpb -= 1
    if gpb * group != k and (gpb * group) % 128 != 0:
        gpb = groups    # no legal split: fall back to whole k
    block_k = gpb * group
    nm, nn, nk = m_pad // block_m, n // block_n, k // block_k

    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, gpb=gpb, group=group),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, gpb, block_n), lambda i, j, kk: (kk, 0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x.dtype),
        scratch_shapes=[
            pl.ANY if pltpu is None
            else pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=interpret,
    )(x, q, scale.astype(jnp.float32).reshape(nk, gpb, n))
    return out[:m] if m_pad != m else out
