"""QTensor-aware Dense layer — the serving-side "kernel-injected Linear".

Reference: the quantized Linear the GPU inference kernels swap in during
module injection (``module_inject/replace_module.py:138`` GroupQuantizer
+ ``csrc/transformer/inference/csrc/pt_binding.cpp`` int8 GEMM). The TPU
design keeps ONE module for both regimes: the param tree decides. A
float ``kernel`` leaf reproduces ``nn.Dense`` numerics bit-for-bit (same
promote_dtype + dot_general), and a :class:`QTensor` leaf routes through
the int8 path, so quantization is a pure tree transformation
(``quantize_tree``) with no module surgery.

Quantized matmul implementation is chosen at trace time:

* ``pallas`` — the tiled dequant-in-VMEM kernel (kernels.int8_matmul);
  the int8 weight streams from HBM, halving decode bandwidth (measured
  1.8x faster than the bf16 matmul at HBM-streaming decode shapes on
  v5e).
* ``xla`` — ``x @ dequant`` under jit. XLA materializes the bf16 weight
  (measured 2-4x slower than bf16 at decode), but every op is standard,
  so it partitions under SPMD sharding.
* ``auto`` (default) — pallas on a single TPU device, xla otherwise
  (pallas_call does not auto-partition under jit SPMD; multi-chip
  quantized serving takes the xla path until the kernel grows a
  custom_partitioning rule).
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quant.quantizer import QTensor


def _quant_impl(impl):
    if impl != "auto":
        return impl
    return "pallas" if (jax.default_backend() == "tpu"
                        and jax.device_count() == 1) else "xla"


def quant_matmul(x, qt, impl="auto"):
    """x [..., k] @ dequant(qt) -> [..., n], impl per module docstring."""
    from deepspeed_tpu.ops.quant.kernels import int8_matmul
    k = x.shape[-1]
    # a trailing partial group (k % scale rows != 0, or an explicit
    # group_size the rows don't tile) has no legal Pallas k-blocking —
    # the dequant-matmul kernel owns whole scale rows per k step.  Route
    # those tensors through the XLA dequant path instead of asserting
    # inside the kernel.
    trailing = k % qt.scale.shape[0] != 0 or (
        qt.group_size is not None and
        qt.group_size * qt.scale.shape[0] != k)
    if trailing or _quant_impl(impl) == "xla":
        return x @ qt.dequant().astype(x.dtype)
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    y = int8_matmul(x.reshape(m, x.shape[-1]), qt.q, qt.scale)
    return y.reshape(*lead, y.shape[-1])


class QDense(nn.Module):
    """Drop-in ``nn.Dense`` with a QTensor fast path (see module doc)."""

    features: int
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    quant_impl: str = "auto"

    @nn.compact
    def __call__(self, inputs):
        kernel = self.param("kernel", self.kernel_init,
                            (jnp.shape(inputs)[-1], self.features),
                            self.param_dtype)
        bias = self.param("bias", self.bias_init, (self.features,),
                          self.param_dtype) if self.use_bias else None
        if isinstance(kernel, QTensor):
            x = inputs.astype(self.dtype or kernel.dtype)
            y = quant_matmul(x, kernel, impl=self.quant_impl)
            if bias is not None:
                y = y + jnp.asarray(bias, y.dtype)
            return y
        # float path: exactly nn.Dense (promote + dot_general + bias)
        inputs, kernel, bias = nn.dtypes.promote_dtype(
            inputs, kernel, bias, dtype=self.dtype)
        y = jax.lax.dot_general(inputs, kernel,
                                (((inputs.ndim - 1,), (0,)), ((), ())))
        if bias is not None:
            y = y + jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
        return y
