from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam  # noqa: F401
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdagrad  # noqa: F401
