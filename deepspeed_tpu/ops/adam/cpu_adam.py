"""Host (CPU) Adam/Adagrad over numpy buffers — the ZeRO-Offload optimizer.

Reference: ``deepspeed/ops/adam/cpu_adam.py:13`` (DeepSpeedCPUAdam) backed
by ``csrc/adam/cpu_adam.cpp``. Here the native kernel is
``csrc/host_adam.cpp`` bound via ctypes; a pure-numpy fallback keeps the
semantics available when no C++ toolchain exists. Unlike the torch
version, this class owns flat fp32 master/moment buffers directly (the
engine keeps only the bf16 compute copy on the chip).
"""

import numpy as np

from deepspeed_tpu.ops.op_builder import CPUAdamBuilder, OpBuilderError

_lib = None
_lib_tried = False


def _native():
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        b = CPUAdamBuilder()
        if b.is_compatible():
            try:
                _lib = b.load()
            except OpBuilderError:
                _lib = None
    return _lib


def _as_f32p(a):
    import ctypes
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _as_u16p(a):
    import ctypes
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class DeepSpeedCPUAdam:
    """Fused host Adam/AdamW stepping fp32 master params in place and
    emitting the bf16 device copy in the same pass."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, fp32_optimizer_states=True):
        self.lr = float(lr)
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.step_count = 0
        self.native = _native() is not None

    def init_state(self, n):
        """(m, v) zero moment buffers for a flat param of n elements."""
        return np.zeros(n, np.float32), np.zeros(n, np.float32)

    def step_flat(self, param, m, v, grad, *, lr=None, grad_scale=1.0,
                  clip_coef=1.0, step=None, bf16_out=None):
        """One Adam step on contiguous fp32 1-D arrays, in place.

        grad is divided by grad_scale then multiplied by clip_coef (the
        reference unscales + clips before its CPU Adam the same way,
        stage_1_and_2.py:1636)."""
        lr = self.lr if lr is None else float(lr)
        step = self.step_count + 1 if step is None else int(step)
        lib = _native()
        if lib is not None:
            lib.ds_adam_step(
                _as_f32p(param), _as_f32p(m), _as_f32p(v), _as_f32p(grad),
                param.size, lr, self.beta1, self.beta2, self.eps,
                self.weight_decay, int(self.adamw_mode), step,
                float(grad_scale), float(clip_coef),
                _as_u16p(bf16_out) if bf16_out is not None else None)
        else:
            g = grad * (clip_coef / grad_scale)
            if not self.adamw_mode and self.weight_decay:
                g = g + self.weight_decay * param
            m *= self.beta1
            m += (1 - self.beta1) * g
            v *= self.beta2
            v += (1 - self.beta2) * g * g
            bc1 = 1 - self.beta1 ** step
            bc2 = 1 - self.beta2 ** step
            denom = np.sqrt(v / bc2) + self.eps
            upd = (lr / bc1) * (m / denom)
            if self.adamw_mode and self.weight_decay:
                upd = upd + lr * self.weight_decay * param
            param -= upd
            if bf16_out is not None:
                bf16_out[:] = f32_to_bf16(param)
        return param

    def advance(self):
        self.step_count += 1


class DeepSpeedCPUAdagrad(DeepSpeedCPUAdam):
    """Host Adagrad (reference deepspeed/ops/adagrad/cpu_adagrad.py)."""

    def init_state(self, n):
        return (np.zeros(n, np.float32),)

    def step_flat(self, param, v, grad, *, lr=None, grad_scale=1.0,
                  clip_coef=1.0, step=None, bf16_out=None):
        lr = self.lr if lr is None else float(lr)
        step = self.step_count + 1 if step is None else int(step)
        lib = _native()
        if lib is not None:
            lib.ds_adagrad_step(
                _as_f32p(param), _as_f32p(v), _as_f32p(grad), param.size,
                lr, self.eps, self.weight_decay, step, float(grad_scale),
                float(clip_coef),
                _as_u16p(bf16_out) if bf16_out is not None else None)
        else:
            g = grad * (clip_coef / grad_scale)
            if self.weight_decay:
                g = g + self.weight_decay * param
            v += g * g
            param -= lr * g / (np.sqrt(v) + self.eps)
            if bf16_out is not None:
                bf16_out[:] = f32_to_bf16(param)
        return param


# ---------------------------------------------------------- flat helpers
def f32_to_bf16(a):
    """Round-to-nearest-even f32 -> bf16 bit pattern (uint16 view)."""
    lib = _native()
    out = np.empty(a.size, np.uint16)
    if lib is not None:
        lib.ds_f32_to_bf16(_as_f32p(a), _as_u16p(out), a.size)
    else:
        bits = a.view(np.uint32)
        rounding = np.uint32(0x7FFF) + ((bits >> 16) & 1)
        out[:] = ((bits + rounding) >> 16).astype(np.uint16)
    return out


def l2_norm_sq(a):
    lib = _native()
    if lib is not None:
        return float(lib.ds_l2_norm_sq(_as_f32p(a), a.size))
    return float(np.dot(a.astype(np.float64), a.astype(np.float64)))


def has_inf_nan(a):
    lib = _native()
    if lib is not None:
        return bool(lib.ds_has_inf_nan(_as_f32p(a), a.size))
    return not bool(np.isfinite(a).all())


def axpy(acc, x):
    lib = _native()
    if lib is not None:
        lib.ds_axpy(_as_f32p(acc), _as_f32p(x), acc.size)
    else:
        acc += x
