"""Shared span tracing, flight recorder and telemetry export.

ONE tracing core for both halves of the framework: the serving tier
(PR 8 — per-request spans, replica fleet traces) and the training tier
(step spans, checkpoint/resume spans, the goodput ledger).  Both export
through the same three surfaces:

1. **Chrome-trace / Perfetto JSON** — :meth:`SpanTracer.to_chrome` /
   :func:`merge_chrome`.  One *process* per OS process / replica /
   training incarnation, one *track* per timeline row (scheduler,
   device, slot N, micro N, ckpt, steps).
2. **Flight recorder** — every tracer keeps its spans in a bounded
   ring; :class:`FlightRecorder` dumps the recent window when the event
   that made it interesting happens (replica death, fault-point firing,
   training stall/straggler, divergence rollback, preemption).
3. **Prometheus text exposition** — :func:`prometheus_text` renders any
   flat ``health()``/``summary()``/ledger dict for external scrapers.
   Metric names are sanitized and label values escaped per the
   exposition-format rules, so arbitrary dict keys cannot emit
   malformed output.

Span timestamps are **host-side** ``time.monotonic()`` readings shifted
to the unix epoch at export (one offset per tracer, so spans from
different processes — serving replicas or training incarnations
separated by a SIGTERM — line up on the wall clock within NTP skew).
Nothing here touches the device: tracing disabled is the shared
:data:`NULL_TRACER` no-op (zero new jit signatures, token-, loss- and
compile-count-identical — pinned by ``tests/unit/test_trace.py`` for
serving and ``tests/unit/test_train_trace.py`` for training).

``serving/trace.py`` re-exports everything here for backward
compatibility and keeps the serving-only pieces (device-profile
integration).
"""

import contextvars
import json
import os
import re
import threading
import time
from collections import deque

from deepspeed_tpu.resilience import faults

# ---------------------------------------------------------------------
# Event taxonomy: every (tag, value, step) event name the serving AND
# training tiers emit through the monitor/ write_events contract.  This
# is an API — dashboards, the CSV sinks and the Prometheus exposition
# key on these names — so tests/unit/test_monitor.py pins that (a)
# everything ServingMetrics/ClusterMetrics emits is listed here and (b)
# every name here is documented in docs/observability.md; the training
# mirror (tests/unit/test_train_trace.py) pins the supervisor's live
# emissions the same way.  Renaming an event without updating both
# fails the pin, not an operator's dashboard.

EVENT_TAXONOMY = {
    # ------------------------------------------------ serving per-step
    "serving/queue_depth": "requests waiting for a slot, per step",
    "serving/running": "live decode slots, per step",
    "serving/waiting": "queued requests, per step (= queue_depth)",
    "serving/page_utilization": "KV page pool occupancy fraction",
    "serving/device_wait_ms": "host time blocked on the device, per step",
    "serving/host_ms": "host bookkeeping time, per step",
    # request latency
    "serving/ttft_ms": "submit -> first token, per request",
    "serving/token_latency_ms": "inter-token gap, per token",
    "serving/tbt_ms": "time between token bursts (horizon cadence)",
    # fused horizons
    "serving/horizon": "fused decode horizon harvested",
    "serving/horizon_tokens": "tokens delivered by one horizon",
    "serving/horizon_wait_ms": "device wait at one horizon's harvest",
    # terminal outcomes (distinct from finished)
    "serving/failed": "request failed (contained per-request error)",
    "serving/shed": "request refused (deadline/capacity)",
    "serving/cancelled": "request cancelled by the client",
    # prefix cache
    "serving/prefix_cache/cached_pages": "pages held by the radix cache",
    "serving/prefix_cache/cached_prefix_tokens":
        "prompt tokens served from cache at one admission",
    "serving/prefix_cache/hit_rate": "admission-time cache hit rate",
    "serving/prefix_cache/prefill_tokens_saved":
        "cumulative prefill tokens not computed",
    "serving/prefix_cache/evicted_pages":
        "cached pages drained under pool pressure",
    # speculative decoding
    "serving/spec/k": "draft K of one verify round",
    "serving/spec/proposed": "draft tokens scored in one round",
    "serving/spec/accepted": "drafts the target argmax matched",
    "serving/spec/emitted": "tokens one verify round produced",
    "serving/spec/acceptance_rate": "per-round acceptance fraction",
    "serving/spec/rollback_tokens": "KV positions rolled back",
    "serving/spec/degraded": "drafter/verify fault contained",
    "serving/spec/wait_ms": "device wait harvesting a verify round",
    # decoding policy (serving/sampling/: per-slot logit pipeline,
    # lossless speculative sampling, grammar-constrained generation)
    "serving/sampling/sampled_requests":
        "cumulative intakes with a sampled/penalized decoding policy",
    "serving/sampling/grammar_requests":
        "cumulative intakes carrying a grammar constraint",
    "serving/sampling/policy_dispatch":
        "one fused dispatch took the policy twins (value = slots)",
    "serving/sampling/grammar_violation":
        "host grammar cursor rejected an emitted token (request failed)",
    # disaggregation
    "serving/handoff": "one prefill->decode KV chain handed off",
    "serving/handoff_tokens": "prefilled positions transferred",
    # handoff transport (cross-pool chain transfers; DCN-tier bytes)
    "serving/comm/handoff_bytes":
        "exact KV payload bytes one chain transfer moved over DCN",
    "serving/handoff/chunks": "chunk dispatches of one chain transfer",
    "serving/handoff/transfer_ms": "wall ms of one chain transfer",
    "serving/handoff/aborted":
        "chain transfer torn down mid-flight (pages freed both sides)",
    # HBM capacity / page-pool attribution (MemTelemetry; the page-state
    # taxonomy is conservation-exact: slot + prefix_shared + prefix_sole
    # + handoff + unattributed + free == num_pages at every step)
    "serving/mem/slot_pages": "pages held as live-slot KV",
    "serving/mem/prefix_shared_pages":
        "prefix-cache pages shared with >= 1 live reader",
    "serving/mem/prefix_sole_pages":
        "prefix-cache pages held by the cache alone (reclaimable)",
    "serving/mem/handoff_pages":
        "pages parked in prefill->decode handoff chains",
    "serving/mem/draft_pages": "draft-model pool pages in use",
    "serving/mem/unattributed_pages":
        "shared-pool pages held by a peer scheduler (0 standalone)",
    "serving/mem/free_pages": "pages on the free list",
    "serving/mem/free_frac": "free fraction of the page pool",
    "serving/mem/page_seconds":
        "cumulative page-seconds integral across all requests",
    "serving/mem/pressure":
        "one capacity-decision causal chain recorded (value = 1)",
    "serving/mem/pressure_episode":
        "sustained-pressure episode fired (free_frac under threshold)",
    # online serving autotuner (OnlineTuner; bounded nudges of the
    # safely-re-resolvable knobs from the live gauge stream)
    "serving/tune/nudge": "one online-tuner knob nudge applied",
    "serving/tune/decode_horizon":
        "live fused-decode horizon cap after a nudge",
    "serving/tune/spec_k": "live speculation-K ceiling after a nudge",
    "serving/tune/prefix_cache_pages":
        "live prefix-cache retention cap after a nudge",
    # serving topology (construction-time gauges; axis set =
    # MeshConfig's known axes)
    "serving/mesh/data": "mesh data-axis size",
    "serving/mesh/model": "mesh model-axis size",
    "serving/mesh/pipe": "mesh pipe-axis size",
    "serving/mesh/expert": "mesh expert-axis size",
    "serving/mesh/sequence": "mesh sequence-axis size",
    "serving/mesh/kv_pool_bytes_per_device":
        "per-device KV pool footprint",
    # ------------------------------------------- cluster (ClusterMetrics)
    "cluster/finished": "journal entry finished",
    "cluster/failed": "journal entry failed",
    "cluster/shed": "journal entry shed",
    "cluster/cancelled": "journal entry cancelled",
    "cluster/heartbeat_miss": "one missed replica heartbeat",
    "cluster/failover": "replica death detected",
    "cluster/replay": "dead replica's entry requeued onto survivors",
    "cluster/retry": "backpressure admission retry",
    "cluster/handoff": "prefill->decode packet delivered",
    "cluster/handoff_degrade": "handoff failed; requeued unified",
    "cluster/handoff_bytes":
        "KV payload bytes one completed chain transfer moved",
    "cluster/handoff_abort":
        "mid-transfer teardown: partial pages freed, requeued unified",
    "cluster/drain": "replica drain completed",
    "cluster/restart": "replica restarted",
    # ------------------------------------------------ router HA (HaMetrics)
    "router/failovers": "cumulative router takeovers (standby promoted)",
    "router/epoch": "current lease epoch (the fencing token)",
    "router/fenced_writes": "WAL appends rejected from stale epochs",
    "router/wal_records": "records accepted by the journal WAL",
    # ------------------------------------------------ training gauges
    "train/step_time_ms": "mean optimizer-step wall time per gauge window",
    "train/samples_per_s": "ThroughputTimer window samples/sec",
    "train/samples_per_s_avg": "ThroughputTimer running-average samples/sec",
    "train/tokens_per_s": "training tokens/sec over one gauge window",
    "train/tflops_achieved": "achieved model TFLOPS over one gauge window",
    "train/mfu": "model flops utilization (achieved / peak) per window",
    # training watchdogs
    "train/straggler": "EWMA step-time anomaly (value = step seconds)",
    "train/stall": "no-progress timer fired (value = seconds stuck)",
    # goodput ledger (fractions of run wall time; sum to 1)
    "train/goodput/productive": "wall fraction in first-time train steps",
    "train/goodput/compile_warmup":
        "wall fraction in steps that compiled a new executable",
    "train/goodput/checkpoint_stall":
        "wall fraction blocked on checkpoint save/verify/rotate",
    "train/goodput/recompute":
        "wall fraction re-running steps already done before a restore",
    "train/goodput/divergence_retry":
        "wall fraction in NaN-watchdog handling and rollback restores",
    "train/goodput/idle":
        "wall fraction in data loading, drain and host bookkeeping",
    # -------------------------------- resilience lifecycle (supervisor)
    "resilience/checkpoint_saved": "verified checkpoint landed (value = step)",
    "resilience/checkpoint_rotated": "retention removed an old tag",
    "resilience/save_retry": "one failed save attempt was retried",
    "resilience/rollback": "a corrupt/unloadable tag was skipped",
    "resilience/resumed": "an intact tag was restored (value = step)",
    "resilience/preempted": "preemption checkpoint landed; run exiting",
    "resilience/nan_loss": "the divergence watchdog saw a non-finite loss",
    # ------------------------------------- communication (HLO ledger)
    # per-signature static-analysis gauges emitted when the serving
    # comm ledger is computed (ServingScheduler.comm_ledger): bytes are
    # per-device wire bytes of ONE steady-state decode dispatch, per
    # the formulas in docs/observability.md
    "serving/comm/bytes_per_step":
        "wire bytes one steady-state decode dispatch moves per device",
    "serving/comm/bytes_per_token":
        "wire bytes per emitted token at full slot occupancy "
        "(bytes_per_step / (horizon x num_slots))",
    "serving/comm/collectives_per_step":
        "collective executions per decode dispatch (trip-weighted)",
    "serving/comm/ici_bytes_per_step":
        "wire bytes riding intra-slice (ICI-tier) groups per dispatch",
    "serving/comm/dcn_bytes_per_step":
        "wire bytes riding cross-process (DCN-tier) groups per dispatch",
    # per-mesh-axis wire-byte split (axis set = MeshConfig's known axes)
    "serving/comm/axis/data": "wire bytes per dispatch on the data axis",
    "serving/comm/axis/model": "wire bytes per dispatch on the model axis",
    "serving/comm/axis/pipe": "wire bytes per dispatch on the pipe axis",
    "serving/comm/axis/expert":
        "wire bytes per dispatch on the expert axis",
    "serving/comm/axis/sequence":
        "wire bytes per dispatch on the sequence axis",
    # recompile watchdog
    "serving/comm/recompile":
        "steady-state recompile detected (value = cumulative count)",
    # ----------------------- sequence-parallel prefill (long context)
    "serving/seq_prefill/routed":
        "a prompt routed onto the sp path (value = pending tokens)",
    "serving/seq_prefill/reserved_pages":
        "pages the routed prompt pre-reserved for its full chain",
    "serving/seq_prefill/chunk_tokens":
        "prompt tokens one sequence-sharded prefill chunk retired",
    "serving/seq_prefill/degraded":
        "a long prompt stayed on the chunked path (no usable axis)",
    "serving/seq_prefill/shed_reserve_cap":
        "a prompt shed on the reserve cap (value = pages it needed)",
    # ----------------------- multi-tenant serving (quotas + fairness)
    "serving/tenant/active":
        "tenants holding at least one pool page this step",
    "serving/tenant/page_seconds":
        "summed page-seconds billed across all tenant ledgers",
    "serving/tenant/max_share":
        "largest single tenant's fraction of the page pool",
    "serving/tenant/quota_shed":
        "a request shed on its tenant's page quota (after self-drain)",
}

# the eager comms logger's periodic report (comm.log_summary) routes
# per-op aggregates through the monitor stream under comm/<op>/<field>
# — the canonical op set below is taxonomy-pinned (custom op_name
# strings still emit, under their own sanitized names)
for _op in ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
            "ppermute", "broadcast", "barrier"):
    EVENT_TAXONOMY[f"comm/{_op}/calls"] = \
        f"eager {_op} invocations accumulated by the comms logger"
    EVENT_TAXONOMY[f"comm/{_op}/bytes"] = (
        f"cumulative message bytes of eager {_op} calls, op-scaled "
        "exactly like the printed log_summary table (calc_bw_log: "
        "gather/scatter count the full buffer, others per member)")
    EVENT_TAXONOMY[f"comm/{_op}/busbw_gbps"] = (
        f"mean bus bandwidth of eager {_op} calls — the raw "
        "calc_bw_log figure, same unit as the comm-ledger row schema "
        "(the printed table shows bits, x8)")
del _op


# ---------------------------------------------------------------- spans

class _NullSpan:
    """Reusable no-op context manager for the disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager recording one complete ("X") span on exit."""

    __slots__ = ("tracer", "name", "cat", "track", "rid", "args",
                 "process", "t0")

    def __init__(self, tracer, name, cat, track, rid, args, process):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.track = track
        self.rid = rid
        self.args = args
        self.process = process
        self.t0 = time.monotonic()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.tracer.complete(self.name, self.t0, time.monotonic(),
                             cat=self.cat, track=self.track, rid=self.rid,
                             args=self.args, process=self.process)
        return False


class SpanTracer:
    """Low-overhead host-side span recorder with a bounded ring buffer.

    The ring (``capacity`` events) makes every tracer double as its own
    flight recorder: a dump after an incident contains the most recent
    window of spans without any always-on file I/O.  All methods are
    no-ops semantically when ``enabled`` is False — but prefer the
    shared :data:`NULL_TRACER` for the disabled case so call sites pay
    one attribute load, not an allocation.
    """

    def __init__(self, process="serve", enabled=True, capacity=8192):
        self.process = process
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        # events are flat tuples (ph, name, cat, ts, dur, track, rid,
        # args, process, flow_id) — recording sits on the serving hot
        # path, so the per-span cost is one tuple + one deque append;
        # dict building is deferred to export
        self.events = deque(maxlen=self.capacity)
        self.dropped = 0          # events rotated out of the ring
        # monotonic -> epoch shift, captured once so exported spans from
        # different processes line up on the wall clock
        self._epoch_offset = time.time() - time.monotonic()

    # ------------------------------------------------------- recording
    def _push(self, ev):
        if len(self.events) == self.capacity:
            self.dropped += 1
        self.events.append(ev)

    def span(self, name, *, cat="serving", track="scheduler", rid=None,
             args=None, process=None):
        """``with tracer.span("prefill_chunk", track=slot, rid=rid):``"""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, track, rid, args, process)

    def complete(self, name, t0, t1, *, cat="serving", track="scheduler",
                 rid=None, args=None, process=None):
        """Record a finished span from two monotonic timestamps (for
        phases whose start predates the call, e.g. queue wait)."""
        if not self.enabled:
            return
        self._push(("X", name, cat, t0, t1 - t0 if t1 > t0 else 0.0,
                    track, rid, args, process, None))

    def instant(self, name, *, cat="serving", track="scheduler", rid=None,
                args=None, process=None, ts=None):
        if not self.enabled:
            return
        self._push(("i", name, cat,
                    time.monotonic() if ts is None else ts, 0.0,
                    track, rid, args, process, None))

    def counter(self, name, values, *, cat="mem", track="counters",
                rid=None, process=None, ts=None):
        """Perfetto *counter track* sample ("C" event): ``values`` is a
        flat {series: number} dict — Perfetto renders one stacked
        counter track per (process, name) with one series per key (the
        page-pool occupancy split rides this).  Samples are cheap flat
        tuples like spans; the dict is only serialized at export."""
        if not self.enabled:
            return
        self._push(("C", name, cat,
                    time.monotonic() if ts is None else ts, 0.0,
                    track, rid, values, process, None))

    def flow(self, phase, flow_id, name, *, cat="failover",
             track="scheduler", rid=None, args=None, process=None):
        """Chrome-trace flow event: ``phase`` 's' starts an arrow,
        'f' finishes it; events sharing ``flow_id`` are linked (the
        explicit dead-replica -> survivor replay link)."""
        if not self.enabled:
            return
        self._push((phase, name, cat, time.monotonic(), 0.0,
                    track, rid, args, process, flow_id))

    # -------------------------------------------------------- exporting
    def serialized(self, drain=False):
        """Events with epoch-resolved timestamps (µs) but unresolved
        process/track labels — the wire format a worker process ships to
        the router's collector.  ``drain=True`` empties the ring (ship
        each span once)."""
        out = []
        src = self.events
        # snapshot defensively: a flight dump may run on a watchdog
        # thread while the owning thread appends spans — retry the
        # (CPython-atomic in practice) copy rather than let a
        # mutated-during-iteration RuntimeError kill the dumping thread
        for _ in range(4):
            try:
                snapshot = list(src)
                break
            except RuntimeError:
                continue
        else:
            snapshot = []
        for ph, name, cat, ts, dur, track, rid, args, process, fid \
                in snapshot:
            e = {"ph": ph, "name": name, "cat": cat,
                 "ts": (ts + self._epoch_offset) * 1e6,
                 "track": track, "rid": rid, "args": args,
                 "process": process or self.process}
            if ph == "X":
                e["dur"] = dur * 1e6
            if fid is not None:
                e["id"] = fid
            out.append(e)
        if drain:
            src.clear()
        return out

    def to_chrome(self, extra_events=None):
        """The full Chrome-trace JSON object for this tracer (merge
        tracers with :func:`merge_chrome`)."""
        return merge_chrome([self.serialized() + list(extra_events or [])])

    def dump(self, path):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
            f.write("\n")
        return path


class _NullTracer(SpanTracer):
    """The disabled tracer: every method is a no-op, ``span`` returns a
    shared no-op context manager.  One module-level instance is shared
    by every untraced scheduler AND every untraced training engine so
    "tracing off" costs one attribute load and one falsy check per call
    site."""

    def __init__(self):
        super().__init__(process="null", enabled=False, capacity=1)

    def _push(self, ev):     # pragma: no cover — nothing may record
        raise AssertionError("NULL_TRACER must never record events")


NULL_TRACER = _NullTracer()


# ------------------------------------------------ compile observability

def jit_cache_size(fn):
    """THE compile-count probe: compiled-signature count of a jitted
    callable (0 for ``None`` or a not-yet-jitted callable).  Every
    consumer — ``InferenceEngine.serving_*_compile_count``,
    ``DeepSpeedEngine.train_compile_counts``, the goodput ledger's
    ``compile_warmup`` detector, the recompile watchdog and the test
    pins — reads THIS helper, so "what counts as a compile" has exactly
    one definition."""
    if fn is None:
        return 0
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return 0
    try:
        return int(probe())
    except Exception:       # a torn-down backend must read as 0, not raise
        return 0


class CompileWatchdog:
    """Recompile detection: jit cache-miss events become ``compile``
    spans, and a *steady-state* recompile — signature churn after
    warmup — fires a tracer instant plus a :class:`FlightRecorder`
    dump (the compile-storm failure class, machine-detected instead of
    test-pinned only).

    Lifecycle: the dispatch layer calls :meth:`on_compile` whenever a
    watched callable's :func:`jit_cache_size` grew across a call
    (``wall_s`` is that call's wall time — jit compiles synchronously
    at dispatch, so the first call's wall IS compile + dispatch).  The
    owner ticks :meth:`step` once per scheduler/train step; after
    ``steady_after_steps`` consecutive ticks without a compile the
    watchdog arms itself (or arm explicitly with :meth:`mark_steady` —
    deterministic for tests and drain boundaries).  Once steady, every
    further compile is a detection: ``recompile_storm`` instant,
    ``serving/comm/recompile`` monitor event (when a metrics funnel is
    bound) and one flight dump naming the recompiled function.

    Host bookkeeping only — it never changes what compiles (pinned by
    ``tests/unit/test_comm_telemetry.py``: watchdog on/off runs are
    token-exact with identical compile counts)."""

    def __init__(self, tracer=None, flight_recorder=None,
                 steady_after_steps=64, metrics=None):
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.flight_recorder = flight_recorder
        self.metrics = metrics          # ServingMetrics-compatible or None
        self.steady = False
        self.steady_after_steps = None if not steady_after_steps \
            else int(steady_after_steps)
        self._quiet_steps = 0
        self.counts = {}                # fn name -> cumulative compiles
        self.compile_wall_s = 0.0       # cumulative compile-attributed wall
        self.steady_recompiles = 0
        # bounded like every other recorder here (SpanTracer ring,
        # FlightRecorder limit): a persistent compile storm — the very
        # scenario this watchdog detects — must not leak memory
        self.events = deque(maxlen=256)  # (name, n, wall_s, steady)
        self._step_idx = 0

    def bind(self, tracer=None, flight_recorder=None, metrics=None):
        if tracer is not None:
            self.tracer = tracer
        if flight_recorder is not None:
            self.flight_recorder = flight_recorder
        if metrics is not None:
            self.metrics = metrics
        return self

    def mark_steady(self):
        """Warmup is over: from here every new jit signature is churn."""
        self.steady = True

    def step(self, owner=None):
        """One scheduler/train step completed (auto-steady ticker).
        With a shared engine-lifetime watchdog, several schedulers
        tick it — pass ``owner`` (the caller's metrics funnel) so only
        the CURRENT owner's steps advance the quiet counter; N
        co-ticking schedulers would otherwise arm steady state in
        1/N-th of the intended warmup window."""
        if owner is not None and self.metrics is not None and \
                owner is not self.metrics:
            return
        self._step_idx += 1
        if self.steady or self.steady_after_steps is None:
            return
        self._quiet_steps += 1
        if self._quiet_steps >= self.steady_after_steps:
            self.steady = True

    def on_compile(self, name, n, t0, t1, detail=None):
        """``n`` new signature(s) of ``name`` compiled during the call
        spanning ``t0``→``t1`` (monotonic seconds)."""
        total = self.counts.get(name, 0) + int(n)
        self.counts[name] = total
        wall = max(t1 - t0, 0.0)
        self.compile_wall_s += wall
        self._quiet_steps = 0
        self.events.append((name, int(n), wall, self.steady))
        args = {"fn": name, "new_signatures": int(n),
                "cumulative": total, "ms": round(wall * 1e3, 3),
                "steady_state": self.steady}
        if detail:
            args.update(detail)
        self.tracer.complete("compile", t0, t1, cat="compile",
                             track="compile", args=args)
        if not self.steady:
            return
        self.steady_recompiles += 1
        self.tracer.instant("recompile_storm", cat="compile",
                            track="compile", args=args)
        if self.metrics is not None:
            rec = getattr(self.metrics, "record_recompile", None)
            if rec is not None:
                rec(self._step_idx, self.steady_recompiles)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                f"recompile:{name}",
                extra={"fn": name, "new_signatures": int(n),
                       "cumulative_compiles": total,
                       "compile_wall_s": round(wall, 4),
                       **({k: v for k, v in (detail or {}).items()})})

    def summary(self):
        return {"compiles": int(sum(self.counts.values())),
                "compile_wall_s": round(self.compile_wall_s, 4),
                "steady": self.steady,
                "steady_recompiles": self.steady_recompiles,
                "per_fn": dict(self.counts)}


# --------------------------------------------------- scoped tracer
# A dynamically-scoped tracer channel for layers whose call signatures
# should not grow a tracer parameter through every seam (the checkpoint
# engine sits behind a pluggable backend API).  The supervisor wraps
# save/load calls in `with scope(tracer):`; checkpoint/engine.py reads
# `current_tracer()` at call time (captured into async-writer closures,
# so the worker thread keeps the caller's tracer).

_scoped = contextvars.ContextVar("ds_tracing_scope", default=NULL_TRACER)


class _TracerScope:
    __slots__ = ("tracer", "_token")

    def __init__(self, tracer):
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._token = None

    def __enter__(self):
        self._token = _scoped.set(self.tracer)
        return self.tracer

    def __exit__(self, *exc):
        _scoped.reset(self._token)
        return False


def scope(tracer):
    """``with tracing.scope(tracer): engine.save_checkpoint(...)``"""
    return _TracerScope(tracer)


def current_tracer():
    return _scoped.get()


def merge_chrome(event_lists):
    """Merge serialized event lists (each from :meth:`SpanTracer.
    serialized`) into one Chrome-trace JSON object: processes become
    pids (with ``process_name`` metadata), (process, track) pairs
    become tids (with ``thread_name`` metadata), flows keep their
    ids."""
    pids = {}
    tids = {}
    out = []

    def pid_for(process):
        if process not in pids:
            pids[process] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name",
                        "pid": pids[process], "tid": 0,
                        "args": {"name": str(process)}})
        return pids[process]

    def tid_for(process, track):
        key = (process, track)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == process]) + 1
            out.append({"ph": "M", "name": "thread_name",
                        "pid": pid_for(process), "tid": tids[key],
                        "args": {"name": track if isinstance(track, str)
                                 else f"slot {track}"}})
        return tids[key]

    for events in event_lists:
        for ev in events:
            process = ev.get("process") or "serve"
            row = {"name": ev["name"], "cat": ev.get("cat", "serving"),
                   "ph": ev["ph"], "ts": ev["ts"],
                   "pid": pid_for(process),
                   "tid": tid_for(process, ev.get("track", "scheduler"))}
            if ev["ph"] == "X":
                row["dur"] = ev.get("dur", 0.0)
            if ev["ph"] == "i":
                row["s"] = "t"      # thread-scoped instant
            # "C" counter samples need no extra fields: Perfetto keys a
            # counter track on (pid, name) and plots one series per
            # args entry (the page-pool state split)
            if "id" in ev:
                row["id"] = ev["id"]
            args = dict(ev.get("args") or {})
            if ev.get("rid") is not None:
                args["rid"] = ev["rid"]
            if args:
                row["args"] = args
            out.append(row)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ------------------------------------------------------ flight recorder

class FlightRecorder:
    """Bounded post-incident dumps of the recent span window.

    Register every tracer in the process (router + one per replica, or
    the training supervisor's tracer); :meth:`dump` writes one JSON
    file per incident into ``out_dir``: the trigger reason, the journal
    entry in flight (when the caller has one — the dead replica's
    replayed request), and the merged recent-span window from every
    registered source.  ``limit`` bounds files per process so an
    incident storm cannot fill a disk.

    Triggers wired by the serving tier:

    * replica death (``ClusterRouter._on_death``),
    * a fault point actually firing (:meth:`arm_fault_observer` hooks
      ``resilience.faults.observe``),
    * an uncontained serving-loop error (``bin/ds_serve``).

    Triggers wired by the training tier (``ResilientTrainer``):

    * the no-progress stall timer and EWMA straggler watchdog,
    * a divergence-watchdog rollback,
    * a checkpoint-corruption rollback during resume,
    * a preemption notice (the final pre-exit window).
    """

    def __init__(self, out_dir, limit=16):
        self.out_dir = out_dir
        self.limit = int(limit)
        self.count = 0
        self.skipped = 0
        self._tracers = {}        # label -> SpanTracer
        self._extra_events = []   # pre-serialized events (dead workers)
        self._fault_observer = None
        self.dumps = []           # paths written
        # dumps arrive from more than one thread now (the training
        # stall watchdog fires from its own daemon thread while the
        # main thread may be dumping a divergence) — the count/limit
        # check and the count-derived filename must be atomic
        self._lock = threading.Lock()

    def register(self, label, source):
        """``source``: a :class:`SpanTracer`, or any callable returning
        a list of pre-serialized events (a ProcessReplica's collected
        worker spans)."""
        self._tracers[label] = source

    def add_events(self, events):
        """Adopt already-serialized span events (e.g. collected from a
        worker process that has since been SIGKILLed)."""
        self._extra_events.extend(events)

    def dump(self, reason, *, journal_entry=None, extra=None):
        """Write one flight record; returns the path (None once
        ``limit`` is reached — the count of skipped dumps is kept).
        Thread-safe: concurrent dumps get distinct indices and never
        exceed ``limit``."""
        with self._lock:
            if self.count >= self.limit:
                self.skipped += 1
                return None
            self.count += 1
            index = self.count
        lists, dropped = [], {}
        for label, src in self._tracers.items():
            lists.append(src.serialized() if hasattr(src, "serialized")
                         else list(src()))
            dropped[label] = getattr(src, "dropped", 0)
        record = {
            "reason": reason,
            "wall_time": time.time(),
            "journal_entry": journal_entry,
            "extra": extra,
            "dropped_spans": dropped,
            "trace": merge_chrome(lists + [self._extra_events]),
        }
        os.makedirs(self.out_dir, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in str(reason))[:64]
        path = os.path.join(self.out_dir,
                            f"flight_{index:03d}_{safe}.json")
        with open(path, "w") as f:
            json.dump(record, f)
            f.write("\n")
        self.dumps.append(path)
        return path

    # ---------------------------------------------------- fault trigger
    def arm_fault_observer(self):
        """Auto-dump whenever a fault point actually FIRES (an armed
        plan's action ran) — the injected chaos is exactly the moment
        the recent-span window is worth keeping."""
        if self._fault_observer is not None:
            return
        def _on_fire(point, ctx):
            self.dump(f"fault:{point}", extra={"ctx": {
                k: v for k, v in ctx.items()
                if isinstance(v, (int, float, str, bool, type(None)))}})
        self._fault_observer = faults.observe(_on_fire)

    def disarm_fault_observer(self):
        if self._fault_observer is not None:
            faults.unobserve(self._fault_observer)
            self._fault_observer = None


# --------------------------------------------------- prometheus export

# Exposition-format rules (https://prometheus.io/docs/instrumenting/
# exposition_formats/): metric and label NAMES match
# [a-zA-Z_:][a-zA-Z0-9_:]*; label VALUES may hold any UTF-8 but
# backslash, double-quote and newline must be escaped.  health() keys
# are arbitrary strings (fault reasons, user tags), so both rules are
# enforced here rather than trusted at every call site.

_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(prefix, key):
    safe = _PROM_BAD_CHARS.sub("_", str(key))
    return f"{prefix}_{safe}"


def _prom_label_name(key):
    safe = _PROM_BAD_CHARS.sub("_", str(key))
    if not safe or safe[0].isdigit():
        safe = "_" + safe
    return safe


def _prom_label_value(value):
    return (str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(metrics, *, prefix="ds_serving", labels=None,
                    help_map=None):
    """Render a flat dict of counters/gauges (``health()`` and/or
    ``summary()`` output, a goodput-ledger dict) in the Prometheus text
    exposition format.

    Non-numeric values (strings, lists, nested dicts, None) are
    skipped — the JSONL health dump carries those; this surface is for
    scrapers.  Booleans export as 0/1.  ``labels`` (dict) are attached
    to every sample, e.g. ``{"replica": "replica0"}``; label values are
    escaped (backslash/quote/newline) and metric/label names sanitized
    (invalid chars -> ``_``) so arbitrary keys cannot emit malformed
    exposition."""
    label_s = ""
    if labels:
        inner = ",".join(
            f'{_prom_label_name(k)}="{_prom_label_value(v)}"'
            for k, v in sorted(labels.items()))
        label_s = "{" + inner + "}"
    lines = []
    for key in sorted(metrics):
        val = metrics[key]
        if isinstance(val, bool):
            val = int(val)
        if not isinstance(val, (int, float)) or val != val:  # skip NaN
            continue
        name = _prom_name(prefix, key)
        if help_map and key in help_map:
            lines.append(f"# HELP {name} {help_map[key]}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{label_s} {val}")
    return "\n".join(lines) + "\n"


# ----------------------------------------------- metrics HTTP endpoint

def start_metrics_server(health_fn, *, summary_fn=None, port=0,
                         prefix="ds_serving", labels=None,
                         host="127.0.0.1"):
    """Serve the Prometheus exposition of ``health_fn()`` (and
    optionally ``summary_fn()`` under ``<prefix>_summary_*``) over a
    stdlib HTTP endpoint — ``GET /metrics`` for scrapers, ``GET
    /healthz`` for the raw health JSON — so the ``.prom``
    textfile-collector dance (``ds_serve --health-interval``) becomes
    optional.  ``port=0`` binds an ephemeral port; read it back from
    ``server.server_port``.  Runs on a daemon thread; call
    ``server.shutdown()`` to stop.  A failing health callable answers
    500 rather than killing the serving loop's thread."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                if self.path.split("?")[0] == "/healthz":
                    body = _json.dumps(health_fn()).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] == "/metrics":
                    text = prometheus_text(health_fn(), prefix=prefix,
                                           labels=labels)
                    if summary_fn is not None:
                        text += prometheus_text(summary_fn(),
                                                prefix=prefix + "_summary",
                                                labels=labels)
                    body = text.encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
            except Exception:   # a broken source must answer, not hang
                self.send_response(500)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):   # scrapers must not spam stderr
            pass

    server = ThreadingHTTPServer((host, int(port)), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
