"""Diffusers (stable-diffusion) attention injection.

Reference: ``deepspeed/module_inject/replace_module.py:182``
``generic_injection`` — walks a diffusers pipeline's UNet/VAE modules
and swaps their ``CrossAttention``/attention blocks for the fused
DeepSpeedDiffusersAttention kernel (containers/unet.py, containers/
vae.py), plus the spatial bias-add kernel (csrc/spatial/
opt_bias_add.cu).

TPU form: diffusion U-Nets in JAX are flax modules whose attention
blocks can simply CALL a fused implementation — so the injectable unit
here is :class:`DiffusersAttention`, a flax drop-in for diffusers'
``Attention`` (q from hidden states, k/v from an optional
encoder-hidden-states context, per-head scaled dot product) that

* ingests diffusers attention weights verbatim
  (``convert_diffusers_attention``: to_q/to_k/to_v/to_out.0), and
* routes SELF-attention through the Pallas flash kernel on TPU (cross
  attention keeps the reference einsum path: its k/v length — text
  tokens, typically 77 — is far below flash block sizes).

``generic_injection(params)`` is the tree-level sweep: it finds every
``to_q/to_k/to_v/to_out`` group in an arbitrary diffusers-layout state
dict and re-lays it for DiffusersAttention, so a whole UNet checkpoint
converts without per-block code (the torch version's module walk,
expressed as the usual pure weight transformation).

The ``diffusers`` python package is NOT required (it is absent from
this environment): everything here operates on plain state dicts and
flax modules. The spatial bias-add fusion needs no kernel at all — XLA
fuses the broadcast add into the producing conv/matmul.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _t(w):
    return np.ascontiguousarray(np.asarray(w).T)


class DiffusersAttention(nn.Module):
    """Drop-in for diffusers ``Attention`` (UNet/VAE blocks)."""

    query_dim: int
    heads: int = 8
    dim_head: int = 64
    cross_attention_dim: Optional[int] = None   # None = self-attention
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, hidden_states, encoder_hidden_states=None):
        inner = self.heads * self.dim_head
        from deepspeed_tpu.ops.quant.qdense import QDense

        def dense(features, name, use_bias=True):
            return QDense(features, dtype=self.dtype, use_bias=use_bias,
                          param_dtype=self.param_dtype, name=name)

        ctx = hidden_states if encoder_hidden_states is None \
            else encoder_hidden_states
        b, lq, _ = hidden_states.shape
        lk = ctx.shape[1]
        q = dense(inner, "to_q", use_bias=False)(hidden_states)
        k = dense(inner, "to_k", use_bias=False)(ctx)
        v = dense(inner, "to_v", use_bias=False)(ctx)
        q = q.reshape(b, lq, self.heads, self.dim_head)
        k = k.reshape(b, lk, self.heads, self.dim_head)
        v = v.reshape(b, lk, self.heads, self.dim_head)
        self_attn = encoder_hidden_states is None
        if self_attn and jax.default_backend() == "tpu" and \
                lq % 128 == 0:
            from deepspeed_tpu.ops.attention import flash_attention
            o = flash_attention(q, k, v, causal=False)
        else:
            from deepspeed_tpu.ops.attention.reference import mha_reference
            o = mha_reference(q, k, v, causal=False)
        o = o.reshape(b, lq, inner)
        return dense(self.query_dim, "to_out")(o)


def convert_diffusers_attention(sd, prefix=""):
    """One diffusers attention block's weights -> DiffusersAttention
    params. ``sd`` holds ``{prefix}to_q.weight`` etc. (torch [out, in]);
    ``to_out.0`` (the Linear inside diffusers' to_out Sequential) maps
    to ``to_out``."""
    g = lambda k: sd[prefix + k]
    return {
        "to_q": {"kernel": _t(g("to_q.weight"))},
        "to_k": {"kernel": _t(g("to_k.weight"))},
        "to_v": {"kernel": _t(g("to_v.weight"))},
        "to_out": {"kernel": _t(g("to_out.0.weight")),
                   "bias": np.asarray(g("to_out.0.bias"))},
    }


def generic_injection(sd):
    """Reference replace_module.py:182 as a state-dict sweep: find every
    attention group (``<base>.to_q.weight`` siblings) in a diffusers
    UNet/VAE checkpoint and convert it; returns {base_path:
    DiffusersAttention params} plus the list of matched blocks."""
    bases = sorted({k[:-len("to_q.weight")] for k in sd
                    if k.endswith("to_q.weight")})
    out = {}
    for base in bases:
        need = [base + s for s in ("to_k.weight", "to_v.weight",
                                   "to_out.0.weight", "to_out.0.bias")]
        if not all(n in sd for n in need):
            continue
        out[base.rstrip(".")] = convert_diffusers_attention(sd, base)
    return out
