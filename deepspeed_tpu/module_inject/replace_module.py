"""HF checkpoint -> native module + params.

Reference: ``deepspeed/module_inject/replace_module.py:274``
(``replace_transformer_layer``) and the sharded-checkpoint loader
(``module_inject/load_checkpoint.py``). The torch version walks a live
model swapping layers; here conversion is whole-model and happens before
any device placement, so TP arrives later as sharding at ``set_params``.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp


def load_hf_state_dict(path):
    """Read an HF checkpoint directory's weights into {name: numpy}.
    Handles single/multi-file safetensors and pytorch_model.bin layouts
    (the reference reads `"checkpoint.json"` shard lists the same way,
    inference/engine.py:335-412)."""
    import json

    def from_safetensors(f):
        from safetensors.numpy import load_file
        try:
            return load_file(f)
        except Exception:
            # bf16 via torch loader when numpy backend refuses the dtype
            from safetensors.torch import load_file as load_torch
            return {k: v.float().numpy()
                    for k, v in load_torch(f).items()}

    def from_torch(f):
        import torch
        sd = torch.load(f, map_location="cpu", weights_only=True)
        return {k: v.float().numpy() if v.dtype == torch.bfloat16
                else v.numpy() for k, v in sd.items()}

    out = {}
    st_index = os.path.join(path, "model.safetensors.index.json")
    pt_index = os.path.join(path, "pytorch_model.bin.index.json")
    if os.path.exists(st_index) or os.path.exists(pt_index):
        index = st_index if os.path.exists(st_index) else pt_index
        with open(index) as f:
            files = sorted(set(json.load(f)["weight_map"].values()))
        for fn in files:
            full = os.path.join(path, fn)
            out.update(from_safetensors(full) if fn.endswith(".safetensors")
                       else from_torch(full))
        return out
    st = os.path.join(path, "model.safetensors")
    if os.path.exists(st):
        return from_safetensors(st)
    pt = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(pt):
        return from_torch(pt)
    raise FileNotFoundError(f"no model weights found under {path}")


def _box_like(template, params):
    """Wrap converted numpy leaves in the module's Partitioned metadata
    (from an eval_shape init) so set_params can derive TP shardings."""
    import flax.linen as nn

    def box(t, leaf):
        if isinstance(t, nn.Partitioned):
            return t.replace_boxed(leaf)
        return leaf

    return jax.tree.map(
        box, template, params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def from_hf(model_or_path, dtype=jnp.float32, hf_config=None):
    """Ingest an HF model: returns (native_module, boxed_params).

    Accepts a transformers PreTrainedModel instance or a local checkpoint
    directory (save_pretrained layout). This is the
    ``replace_transformer_layer`` capability — serve models trained
    elsewhere — as a one-shot conversion.
    """
    if isinstance(model_or_path, str):
        from transformers import AutoConfig
        cfg = hf_config or AutoConfig.from_pretrained(model_or_path)
        sd = load_hf_state_dict(model_or_path)
    else:
        cfg = hf_config or model_or_path.config
        sd = {k: v.detach().cpu().float().numpy()
              for k, v in model_or_path.state_dict().items()}

    from deepspeed_tpu.module_inject.replace_policy import policy_for
    try:
        pol = policy_for(cfg)
        module = pol.build_module(cfg, dtype=dtype)
        params = pol.convert(cfg, sd)
    except ValueError as policy_err:
        # generic structural fallback (reference auto_tp.py:13): unknown
        # architectures whose state dict is a llama-shaped decoder
        from deepspeed_tpu.module_inject.policy import AutoTPPolicy
        if AutoTPPolicy.discover(sd) is None:
            raise policy_err
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"no policy for model_type="
            f"{getattr(cfg, 'model_type', None)!r}; using the AutoTP "
            "structural fallback (llama-shaped decoder discovered)")
        module, params = AutoTPPolicy.ingest(cfg, sd, dtype=dtype)
    params = jax.tree.map(lambda x: np.asarray(x, jnp.dtype(dtype)), params)

    # shape/dtype template with Partitioned metadata, no real compute
    ids = jnp.zeros((1, 8), jnp.int32)
    template = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0), ids))["params"]
    _check_structure(template, params)
    return module, _box_like(template, params)


def _check_structure(template, params):
    import flax.linen as nn
    t_flat = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(lambda x: 0, template,
                     is_leaf=lambda x: isinstance(x, nn.Partitioned)))[0]
    p_flat = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(lambda x: 0, params))[0]
    t_keys = {jax.tree_util.keystr(k) for k, _ in t_flat}
    p_keys = {jax.tree_util.keystr(k) for k, _ in p_flat}
    if t_keys != p_keys:
        missing = sorted(t_keys - p_keys)[:5]
        extra = sorted(p_keys - t_keys)[:5]
        raise ValueError(
            f"converted params do not match the native module: "
            f"missing={missing} extra={extra}")
    tmpl_shapes = {jax.tree_util.keystr(k): np.shape(v)
                   for k, v in jax.tree_util.tree_flatten_with_path(
                       jax.tree.map(
                           lambda x: x.value
                           if isinstance(x, nn.Partitioned) else x,
                           template,
                           is_leaf=lambda x: isinstance(x, nn.Partitioned))
                   )[0]}
    for k, v in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = jax.tree_util.keystr(k)
        if tuple(tmpl_shapes[key]) != tuple(np.shape(v)):
            raise ValueError(f"shape mismatch for {key}: converted "
                             f"{np.shape(v)} vs module {tmpl_shapes[key]}")


def replace_transformer_layer(model, dtype=jnp.float32, **_):
    """Reference-named alias (replace_module.py:274): converts a whole HF
    model instead of swapping layers in place."""
    return from_hf(model, dtype=dtype)
