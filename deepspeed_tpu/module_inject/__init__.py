"""HF-model ingestion: policy system + checkpoint conversion.

Reference: ``deepspeed/module_inject/`` — ``TransformerPolicy``
(policy.py:42), the ``replace_policy`` registry, per-architecture weight
containers (containers/*.py), and ``replace_transformer_layer``
(replace_module.py:274) which swaps HF modules for kernel-injected ones
with TP-sliced weights.

TPU redesign: instead of swapping submodules inside a live torch model,
the policy maps a whole HF architecture (config + state dict) onto the
equivalent *native* flax module and converts the weights once. TP slicing
disappears — converted params carry logical-axis metadata, so `pjit`
shards them over the `model` mesh axis at `set_params`
(the `ReplaceWithTensorSlicing`/`AutoTP` capability as sharding specs).
"""

from deepspeed_tpu.module_inject.replace_module import (  # noqa: F401
    from_hf, load_hf_state_dict, replace_transformer_layer)
from deepspeed_tpu.module_inject.replace_policy import (  # noqa: F401
    POLICIES, policy_for)
