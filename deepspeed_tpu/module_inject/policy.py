"""Per-architecture ingestion policies.

Reference: ``deepspeed/module_inject/policy.py:42`` (``TransformerPolicy``
— knows where an architecture keeps qkv/o/mlp weights) and the container
classes under ``module_inject/containers/`` (one per HF family). Here a
policy is: HF config -> native config + flax module, and HF state dict ->
native param tree. All arrays are numpy; transposes happen here so the
native modules stay layout-clean ([in, out] kernels everywhere).
"""

import numpy as np

import jax.numpy as jnp


def _t(w):
    """HF nn.Linear stores [out, in]; flax Dense kernels are [in, out]."""
    return np.ascontiguousarray(np.asarray(w).T)


def _np(w):
    return np.asarray(w)


class InjectionPolicy:
    """Base policy: subclass per HF model_type."""

    model_type = None          # HF config.model_type this policy matches

    @classmethod
    def matches(cls, hf_config):
        return getattr(hf_config, "model_type", None) == cls.model_type

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        """Native flax module equivalent to the HF architecture."""
        raise NotImplementedError

    @classmethod
    def convert(cls, hf_config, sd):
        """HF state dict (name -> numpy) -> native param tree (nested
        dicts of numpy arrays matching build_module's param structure)."""
        raise NotImplementedError


class GPT2Policy(InjectionPolicy):
    """HF GPT2LMHeadModel (reference containers/gpt2.py: HFGPT2LayerPolicy).
    GPT-2's Conv1D already stores [in, out]; no transposes needed."""

    model_type = "gpt2"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.n_embd,
            num_layers=c.n_layer, num_heads=c.n_head,
            max_seq_len=c.n_positions,
            layer_norm_eps=c.layer_norm_epsilon,
            activation="gelu",            # HF gelu_new == tanh approximation
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        p = {"wte": _np(sd["transformer.wte.weight"]),
             "wpe": _np(sd["transformer.wpe.weight"]),
             "ln_f": {"scale": _np(sd["transformer.ln_f.weight"]),
                      "bias": _np(sd["transformer.ln_f.bias"])}}
        for i in range(hf_config.n_layer):
            h = f"transformer.h.{i}."
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "ln_1.weight"]),
                         "bias": _np(sd[h + "ln_1.bias"])},
                "ln_2": {"scale": _np(sd[h + "ln_2.weight"]),
                         "bias": _np(sd[h + "ln_2.bias"])},
                "attn": {
                    "qkv": {"kernel": _np(sd[h + "attn.c_attn.weight"]),
                            "bias": _np(sd[h + "attn.c_attn.bias"])},
                    "proj": {"kernel": _np(sd[h + "attn.c_proj.weight"]),
                             "bias": _np(sd[h + "attn.c_proj.bias"])}},
                "mlp": {
                    "fc_in": {"kernel": _np(sd[h + "mlp.c_fc.weight"]),
                              "bias": _np(sd[h + "mlp.c_fc.bias"])},
                    "fc_out": {"kernel": _np(sd[h + "mlp.c_proj.weight"]),
                               "bias": _np(sd[h + "mlp.c_proj.bias"])}},
            }
        return p


class OPTPolicy(InjectionPolicy):
    """HF OPTForCausalLM (reference containers/opt.py: HFOPTLayerPolicy).
    Separate q/k/v Linears fuse into the native qkv kernel; learned
    positions keep OPT's +2 storage offset."""

    model_type = "opt"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        if getattr(c, "word_embed_proj_dim", c.hidden_size) != c.hidden_size:
            raise ValueError("OPT variants with word_embed_proj_dim != "
                             "hidden_size (350m) are not supported")
        if not getattr(c, "do_layer_norm_before", True):
            raise ValueError("post-layernorm OPT variants (350m) are not "
                             "supported")
        assert c.ffn_dim % c.hidden_size == 0
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_hidden_layers, num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            mlp_ratio=c.ffn_dim // c.hidden_size,
            layer_norm_eps=1e-5, activation="relu", pos_offset=2,
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        d = "model.decoder."
        if d + "final_layer_norm.weight" not in sd:
            d2 = "decoder." if "decoder.embed_tokens.weight" in sd else d
            d = d2
        p = {"wte": _np(sd[d + "embed_tokens.weight"]),
             "wpe": _np(sd[d + "embed_positions.weight"]),
             "ln_f": {"scale": _np(sd[d + "final_layer_norm.weight"]),
                      "bias": _np(sd[d + "final_layer_norm.bias"])}}
        for i in range(hf_config.num_hidden_layers):
            h = f"{d}layers.{i}."
            qkv_w = np.concatenate(
                [_t(sd[h + f"self_attn.{n}_proj.weight"])
                 for n in ("q", "k", "v")], axis=1)
            qkv_b = np.concatenate(
                [_np(sd[h + f"self_attn.{n}_proj.bias"])
                 for n in ("q", "k", "v")])
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "self_attn_layer_norm.weight"]),
                         "bias": _np(sd[h + "self_attn_layer_norm.bias"])},
                "ln_2": {"scale": _np(sd[h + "final_layer_norm.weight"]),
                         "bias": _np(sd[h + "final_layer_norm.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {"kernel": _t(sd[h + "self_attn.out_proj.weight"]),
                             "bias": _np(sd[h + "self_attn.out_proj.bias"])}},
                "mlp": {
                    "fc_in": {"kernel": _t(sd[h + "fc1.weight"]),
                              "bias": _np(sd[h + "fc1.bias"])},
                    "fc_out": {"kernel": _t(sd[h + "fc2.weight"]),
                               "bias": _np(sd[h + "fc2.bias"])}},
            }
        return p


class BloomPolicy(InjectionPolicy):
    """HF BloomForCausalLM (reference containers/bloom.py: BLOOMLayerPolicy).
    ALiBi attention, no positional table, embedding layernorm; the fused
    query_key_value weight is stored head-interleaved [(h, 3, d), in] and is
    reordered to the native contiguous-q|k|v layout."""

    model_type = "bloom"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.n_layer, num_heads=c.n_head,
            max_seq_len=getattr(c, "seq_length", 2048),
            layer_norm_eps=c.layer_norm_epsilon,
            activation="gelu",            # BloomGelu is the tanh approximation
            pos_embed="none", use_alibi=True, embed_layernorm=True,
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def _split_qkv(cls, w, b, n_head):
        """[3h, in] head-interleaved -> [in, 3h] contiguous q|k|v."""
        three_h, h_in = w.shape
        d = three_h // (3 * n_head)
        w = w.reshape(n_head, 3, d, h_in).transpose(1, 0, 2, 3) \
             .reshape(3 * n_head * d, h_in)
        b = b.reshape(n_head, 3, d).transpose(1, 0, 2).reshape(-1)
        return _t(w), np.ascontiguousarray(b)

    @classmethod
    def convert(cls, hf_config, sd):
        t = "transformer." if "transformer.word_embeddings.weight" in sd \
            else ""
        p = {"wte": _np(sd[t + "word_embeddings.weight"]),
             "ln_embed": {
                 "scale": _np(sd[t + "word_embeddings_layernorm.weight"]),
                 "bias": _np(sd[t + "word_embeddings_layernorm.bias"])},
             "ln_f": {"scale": _np(sd[t + "ln_f.weight"]),
                      "bias": _np(sd[t + "ln_f.bias"])}}
        for i in range(hf_config.n_layer):
            h = f"{t}h.{i}."
            qkv_w, qkv_b = cls._split_qkv(
                _np(sd[h + "self_attention.query_key_value.weight"]),
                _np(sd[h + "self_attention.query_key_value.bias"]),
                hf_config.n_head)
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "input_layernorm.weight"]),
                         "bias": _np(sd[h + "input_layernorm.bias"])},
                "ln_2": {
                    "scale": _np(sd[h + "post_attention_layernorm.weight"]),
                    "bias": _np(sd[h + "post_attention_layernorm.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {"kernel": _t(sd[h + "self_attention.dense.weight"]),
                             "bias": _np(sd[h + "self_attention.dense.bias"])}},
                "mlp": {
                    "fc_in": {"kernel": _t(sd[h + "mlp.dense_h_to_4h.weight"]),
                              "bias": _np(sd[h + "mlp.dense_h_to_4h.bias"])},
                    "fc_out": {"kernel": _t(sd[h + "mlp.dense_4h_to_h.weight"]),
                               "bias": _np(sd[h + "mlp.dense_4h_to_h.bias"])}},
            }
        return p


class GPTJPolicy(InjectionPolicy):
    """HF GPTJForCausalLM (reference containers/gptj.py: HFGPTJLayerPolicy).
    Interleaved partial rotary, parallel residual with a single layernorm,
    bias-free attention projections, untied lm_head WITH a bias."""

    model_type = "gptj"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        n_inner = getattr(c, "n_inner", None) or 4 * c.n_embd
        if n_inner % c.n_embd:
            raise ValueError(f"GPT-J n_inner {n_inner} must be a multiple "
                             f"of n_embd {c.n_embd}")
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.n_embd,
            num_layers=c.n_layer, num_heads=c.n_head,
            max_seq_len=c.n_positions,
            mlp_ratio=n_inner // c.n_embd,
            layer_norm_eps=c.layer_norm_epsilon,
            activation="gelu",            # gelu_new
            pos_embed="none", rotary_dim=c.rotary_dim,
            rotary_interleaved=True, parallel_residual=True, single_ln=True,
            attn_bias=False, tie_embeddings=False, lm_head_bias=True,
            dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        p = {"wte": _np(sd["transformer.wte.weight"]),
             "ln_f": {"scale": _np(sd["transformer.ln_f.weight"]),
                      "bias": _np(sd["transformer.ln_f.bias"])},
             "lm_head": {"kernel": _t(sd["lm_head.weight"]),
                         "bias": _np(sd["lm_head.bias"])}}
        for i in range(hf_config.n_layer):
            h = f"transformer.h.{i}."
            qkv_w = np.concatenate(
                [_t(sd[h + f"attn.{n}_proj.weight"])
                 for n in ("q", "k", "v")], axis=1)
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "ln_1.weight"]),
                         "bias": _np(sd[h + "ln_1.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w},
                    "proj": {"kernel": _t(sd[h + "attn.out_proj.weight"])}},
                "mlp": {
                    "fc_in": {"kernel": _t(sd[h + "mlp.fc_in.weight"]),
                              "bias": _np(sd[h + "mlp.fc_in.bias"])},
                    "fc_out": {"kernel": _t(sd[h + "mlp.fc_out.weight"]),
                               "bias": _np(sd[h + "mlp.fc_out.bias"])}},
            }
        return p


class GPTNeoXPolicy(InjectionPolicy):
    """HF GPTNeoXForCausalLM (reference containers/gptneox.py). Partial
    rotate-half rotary (rotary_pct), parallel residual with two
    layernorms, head-interleaved fused qkv (BLOOM layout), untied
    embed_out."""

    model_type = "gpt_neox"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        head_dim = c.hidden_size // c.num_attention_heads
        assert c.intermediate_size % c.hidden_size == 0
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            mlp_ratio=c.intermediate_size // c.hidden_size,
            layer_norm_eps=c.layer_norm_eps,
            # HF NeoX hidden_act "gelu" is the exact erf gelu
            activation="gelu_exact" if c.hidden_act == "gelu" else "gelu",
            pos_embed="none",
            rotary_dim=int(head_dim * c.rotary_pct),
            rope_base=getattr(c, "rotary_emb_base", 10000.0),
            parallel_residual=bool(getattr(c, "use_parallel_residual",
                                           True)),
            tie_embeddings=bool(getattr(c, "tie_word_embeddings", False)),
            dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        p = {"wte": _np(sd["gpt_neox.embed_in.weight"]),
             "ln_f": {"scale": _np(sd["gpt_neox.final_layer_norm.weight"]),
                      "bias": _np(sd["gpt_neox.final_layer_norm.bias"])}}
        if not getattr(hf_config, "tie_word_embeddings", False):
            p["lm_head"] = {"kernel": _t(sd["embed_out.weight"])}
        for i in range(hf_config.num_hidden_layers):
            h = f"gpt_neox.layers.{i}."
            qkv_w, qkv_b = BloomPolicy._split_qkv(
                _np(sd[h + "attention.query_key_value.weight"]),
                _np(sd[h + "attention.query_key_value.bias"]),
                hf_config.num_attention_heads)
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "input_layernorm.weight"]),
                         "bias": _np(sd[h + "input_layernorm.bias"])},
                "ln_2": {
                    "scale": _np(sd[h + "post_attention_layernorm.weight"]),
                    "bias": _np(sd[h + "post_attention_layernorm.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {"kernel": _t(sd[h + "attention.dense.weight"]),
                             "bias": _np(sd[h + "attention.dense.bias"])}},
                "mlp": {
                    "fc_in": {"kernel": _t(sd[h + "mlp.dense_h_to_4h.weight"]),
                              "bias": _np(sd[h + "mlp.dense_h_to_4h.bias"])},
                    "fc_out": {"kernel": _t(sd[h + "mlp.dense_4h_to_h.weight"]),
                               "bias": _np(sd[h + "mlp.dense_4h_to_h.bias"])}},
            }
        return p


class GPTNeoPolicy(InjectionPolicy):
    """HF GPTNeoForCausalLM (reference containers/gptneo.py:
    HFGPTNEOLayerPolicy). Alternating global/local (sliding-window)
    attention per ``attention_types``; separate unbiased q/k/v with a
    biased out_proj. GPT-Neo was trained WITHOUT the 1/sqrt(head_dim)
    attention scale, so convert() pre-scales the q projection by
    sqrt(head_dim) to cancel the native module's scaling."""

    model_type = "gpt_neo"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        inter = getattr(c, "intermediate_size", None) or 4 * c.hidden_size
        assert inter % c.hidden_size == 0
        # expand attention_types ([["global","local"], n/2] pairs) into
        # the per-layer window tuple
        pattern = []
        for kinds, times in c.attention_types:
            pattern += list(kinds) * times
        windows = tuple(c.window_size if k == "local" else 0
                        for k in pattern)
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_layers, num_heads=c.num_heads,
            max_seq_len=c.max_position_embeddings,
            mlp_ratio=inter // c.hidden_size,
            layer_norm_eps=c.layer_norm_epsilon,
            activation="gelu",            # gelu_new
            qkv_bias=False, attn_windows=windows,
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        p = {"wte": _np(sd["transformer.wte.weight"]),
             "wpe": _np(sd["transformer.wpe.weight"]),
             "ln_f": {"scale": _np(sd["transformer.ln_f.weight"]),
                      "bias": _np(sd["transformer.ln_f.bias"])}}
        head_dim = hf_config.hidden_size // hf_config.num_heads
        # HF GPT-Neo attention does NOT divide scores by sqrt(head_dim);
        # fold the compensation into the q projection
        q_scale = float(np.sqrt(head_dim))
        for i in range(hf_config.num_layers):
            h = f"transformer.h.{i}."
            qkv_w = np.concatenate(
                [_t(sd[h + f"attn.attention.{n}_proj.weight"]) *
                 (q_scale if n == "q" else 1.0)
                 for n in ("q", "k", "v")], axis=1).astype(np.float32)
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "ln_1.weight"]),
                         "bias": _np(sd[h + "ln_1.bias"])},
                "ln_2": {"scale": _np(sd[h + "ln_2.weight"]),
                         "bias": _np(sd[h + "ln_2.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w},
                    "proj": {
                        "kernel": _t(sd[h + "attn.attention.out_proj.weight"]),
                        "bias": _np(sd[h + "attn.attention.out_proj.bias"])}},
                "mlp": {
                    "fc_in": {"kernel": _t(sd[h + "mlp.c_fc.weight"]),
                              "bias": _np(sd[h + "mlp.c_fc.bias"])},
                    "fc_out": {"kernel": _t(sd[h + "mlp.c_proj.weight"]),
                               "bias": _np(sd[h + "mlp.c_proj.bias"])}},
            }
        return p


class MegatronGPT2Policy(InjectionPolicy):
    """Megatron-LM GPT-2 checkpoints (reference containers/megatron_gpt.py:
    MegatronLayerPolicy). Matched by the Megatron state-dict key layout
    (``language_model.transformer.layers.N.*``) rather than an HF
    model_type.

    The fused query_key_value layout depends on the Megatron-LM source
    generation (reference containers/features/megatron.py:16
    ``transpose_qkv_alignment``). Megatron's own ``checkpoint_version``
    metadata distinguishes THREE fused-dim layouts (the same ones
    transformers' ``fix_query_key_value_ordering`` handles):

    * ``< 1.0``  — contiguous ``q|k|v`` = ``(3, heads, hd)``: the target
      layout, transpose only.
    * ``1.0``    — ``(heads, hd, 3)``.
    * ``>= 2.0`` — per-head interleaved ``(heads, 3, hd)`` like BLOOM;
      this is what the reference's ``megatron_v2 = True`` default assumes.

    All three have identical tensor shapes, so they cannot be
    distinguished structurally — we read the checkpoint's own metadata:
    an explicit ``megatron_v2`` bool attr on the config wins (True →
    ``(heads, 3, hd)``, False → contiguous, mirroring the reference
    flag), else ``checkpoint_version`` (a key Megatron writes into its
    checkpoints, also accepted as a config attr), else default to the
    v2 layout like the reference (MegatronLayerPolicy.megatron_v2)."""

    model_type = "megatron-lm"

    @staticmethod
    def _qkv_layout(hf_config, sd):
        """-> 'contiguous' | 'v1' | 'v2' (fused-dim layout, see class doc)."""
        v2 = getattr(hf_config, "megatron_v2", None)
        if v2 is not None:
            return "v2" if v2 else "contiguous"
        ver = sd.get("checkpoint_version",
                     getattr(hf_config, "checkpoint_version", None))
        if ver is None:
            return "v2"
        ver = float(ver)
        if ver >= 2.0:
            return "v2"
        return "v1" if ver >= 1.0 else "contiguous"

    @staticmethod
    def _split_qkv_v1(w, b, n_head):
        """(heads, hd, 3) fused layout -> [in, 3h] contiguous q|k|v."""
        three_h, h_in = w.shape
        d = three_h // (3 * n_head)
        w = w.reshape(n_head, d, 3, h_in).transpose(2, 0, 1, 3) \
             .reshape(3 * n_head * d, h_in)
        b = b.reshape(n_head, d, 3).transpose(2, 0, 1).reshape(-1)
        return _t(w), np.ascontiguousarray(b)

    @classmethod
    def matches(cls, hf_config):
        return getattr(hf_config, "model_type", None) in (
            "megatron-lm", "megatron_gpt2", "megatron")

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        inter = getattr(c, "ffn_hidden_size", None) or 4 * c.hidden_size
        assert inter % c.hidden_size == 0
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            mlp_ratio=inter // c.hidden_size,
            layer_norm_eps=getattr(c, "layernorm_epsilon", 1e-5),
            activation="gelu",
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        lm = "language_model."
        if lm + "embedding.word_embeddings.weight" not in sd and \
                "embedding.word_embeddings.weight" in sd:
            lm = ""
        e = lm + "embedding."
        t = lm + "transformer."
        p = {"wte": _np(sd[e + "word_embeddings.weight"]),
             "wpe": _np(sd[e + "position_embeddings.weight"]),
             "ln_f": {"scale": _np(sd[t + "final_layernorm.weight"]),
                      "bias": _np(sd[t + "final_layernorm.bias"])}}
        layout = cls._qkv_layout(hf_config, sd)
        for i in range(hf_config.num_layers):
            h = f"{t}layers.{i}."
            w = _np(sd[h + "attention.query_key_value.weight"])
            b = _np(sd[h + "attention.query_key_value.bias"])
            if layout == "v2":     # per-head (heads, 3, hd) -> q|k|v
                qkv_w, qkv_b = BloomPolicy._split_qkv(
                    w, b, hf_config.num_attention_heads)
            elif layout == "v1":   # (heads, hd, 3) -> q|k|v
                qkv_w, qkv_b = cls._split_qkv_v1(
                    w, b, hf_config.num_attention_heads)
            else:                  # already contiguous q|k|v
                qkv_w, qkv_b = _t(w), np.ascontiguousarray(b)
            p[f"h_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "input_layernorm.weight"]),
                         "bias": _np(sd[h + "input_layernorm.bias"])},
                "ln_2": {
                    "scale": _np(sd[h + "post_attention_layernorm.weight"]),
                    "bias": _np(sd[h + "post_attention_layernorm.bias"])},
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {"kernel": _t(sd[h + "attention.dense.weight"]),
                             "bias": _np(sd[h + "attention.dense.bias"])}},
                **cls._layer_mlp(hf_config, sd, h, i),
            }
        return p

    @classmethod
    def _layer_mlp(cls, hf_config, sd, h, i):
        """The layer's FFN subtree — the MoE subclass swaps this per
        layer (reference megatron_gpt_moe.py replaces the container's
        mlp with deepspeed_moe experts)."""
        return {"mlp": {
            "fc_in": {
                "kernel": _t(sd[h + "mlp.dense_h_to_4h.weight"]),
                "bias": _np(sd[h + "mlp.dense_h_to_4h.bias"])},
            "fc_out": {
                "kernel": _t(sd[h + "mlp.dense_4h_to_h.weight"]),
                "bias": _np(sd[h + "mlp.dense_4h_to_h.bias"])}}}


class MegatronGPTMoEPolicy(MegatronGPT2Policy):
    """Megatron-DeepSpeed MoE checkpoints (reference
    ``module_inject/containers/megatron_gpt_moe.py:1`` DS_MegatronGPTMoE
    + ``policy.mlp(moe_type)`` extracting per-expert
    ``mlp.deepspeed_moe.experts.deepspeed_experts.<i>.*`` weights and the
    ``gate.wg`` projection).

    TPU form: the per-expert torch Linears stack into ExpertsMLP's
    ``[e, ...]`` leaves (moe/layer.py:52 — "expert" is a sharding axis,
    so expert-parallel serving needs no process groups; the decode
    all-to-alls are XLA collectives at the sharding constraints). MoE
    layer placement is DETECTED from the state dict (which layers carry
    ``deepspeed_moe`` keys) and must match the model's every-Nth-block
    pattern (GPTConfig.moe_every). PR-MoE residual branches
    (``mlp.mlp.*`` + ``coefficient``) map onto the QDense residual pair.
    Attention/layernorm/embedding conversion inherits MegatronGPT2Policy
    (same fused-qkv layout rules)."""

    model_type = "megatron-moe"

    @classmethod
    def matches(cls, hf_config):
        return getattr(hf_config, "model_type", None) in (
            "megatron-moe", "megatron_gpt_moe", "megatron-deepspeed-moe")

    @staticmethod
    def _moe_layers(sd):
        import re
        layers = set()
        for k in sd:
            m = re.search(r"layers\.(\d+)\..*deepspeed_moe", k)
            if m:
                layers.add(int(m.group(1)))
        return sorted(layers)

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.gpt2 import GPT2, GPTConfig
        c = hf_config
        inter = getattr(c, "ffn_hidden_size", None) or 4 * c.hidden_size
        assert inter % c.hidden_size == 0
        cfg = GPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            mlp_ratio=inter // c.hidden_size,
            layer_norm_eps=getattr(c, "layernorm_epsilon", 1e-5),
            activation="gelu",
            moe_num_experts=c.num_experts,
            moe_top_k=getattr(c, "moe_top_k", 1),
            moe_every=getattr(c, "moe_every", 2),
            moe_use_residual=getattr(c, "moe_use_residual", False),
            tie_embeddings=True, dtype=dtype, param_dtype=dtype)
        return GPT2(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        moe_layers = cls._moe_layers(sd)
        every = getattr(hf_config, "moe_every", 2)
        want = [i for i in range(hf_config.num_layers)
                if i % every == every - 1]
        if moe_layers != want:
            raise ValueError(
                f"MoE layers in checkpoint {moe_layers} do not match the "
                f"every-{every}th-block pattern {want}; set moe_every on "
                "the config to the checkpoint's expert interval")
        return super().convert(hf_config, sd)

    @classmethod
    def _layer_mlp(cls, hf_config, sd, h, i):
        every = getattr(hf_config, "moe_every", 2)
        if i % every != every - 1:     # dense block
            return super()._layer_mlp(hf_config, sd, h, i)
        e = hf_config.num_experts
        moe = h + "mlp.deepspeed_moe."
        ex = moe + "experts.deepspeed_experts."
        out = {
            "gate": _t(sd[moe + "gate.wg.weight"]).astype(np.float32),
            "experts": {
                "wi": np.stack([_t(sd[f"{ex}{j}.dense_h_to_4h.weight"])
                                for j in range(e)]),
                "bi": np.stack([_np(sd[f"{ex}{j}.dense_h_to_4h.bias"])
                                for j in range(e)]),
                "wo": np.stack([_t(sd[f"{ex}{j}.dense_4h_to_h.weight"])
                                for j in range(e)]),
                "bo": np.stack([_np(sd[f"{ex}{j}.dense_4h_to_h.bias"])
                                for j in range(e)])},
        }
        if getattr(hf_config, "moe_use_residual", False):
            # PR-MoE residual branch (reference megatron_gpt_moe.py:27
            # moe_type != standard: mlp.mlp.* + coefficient)
            out["res_fc_in"] = {
                "kernel": _t(sd[h + "mlp.mlp.dense_h_to_4h.weight"]),
                "bias": _np(sd[h + "mlp.mlp.dense_h_to_4h.bias"])}
            out["res_fc_out"] = {
                "kernel": _t(sd[h + "mlp.mlp.dense_4h_to_h.weight"]),
                "bias": _np(sd[h + "mlp.mlp.dense_4h_to_h.bias"])}
            out["coefficient"] = {
                "kernel": _t(sd[h + "mlp.coefficient.weight"]).astype(
                    np.float32),
                "bias": _np(sd[h + "mlp.coefficient.bias"]).astype(
                    np.float32)}
        return {"moe": out}


class CLIPPolicy(InjectionPolicy):
    """HF CLIPTextModel (reference containers/clip.py HFCLIPLayerPolicy
    — the stable-diffusion text tower of the generic_injection path,
    replace_module.py:182). Separate q/k/v/out projections transpose
    straight into the native CLIPText layout."""

    model_type = "clip_text_model"

    @classmethod
    def matches(cls, hf_config):
        return getattr(hf_config, "model_type", None) in (
            "clip_text_model", "clip")

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.clip import CLIPText, CLIPTextConfig
        c = hf_config
        if getattr(c, "text_config", None) is not None:   # full CLIPConfig
            c = c.text_config
        assert (getattr(c, "hidden_act", "quick_gelu")
                == "quick_gelu"), "CLIPText implements quick_gelu"
        cfg = CLIPTextConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            intermediate_size=c.intermediate_size,
            num_layers=c.num_hidden_layers,
            num_heads=c.num_attention_heads,
            max_seq_len=c.max_position_embeddings,
            layer_norm_eps=c.layer_norm_eps, dtype=dtype, param_dtype=dtype)
        return CLIPText(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        c = hf_config
        if getattr(c, "text_config", None) is not None:
            c = c.text_config
        t = "text_model." if any(k.startswith("text_model.") for k in sd) \
            else ""
        p = {"token_embedding":
                 _np(sd[t + "embeddings.token_embedding.weight"]),
             "position_embedding":
                 _np(sd[t + "embeddings.position_embedding.weight"]),
             "final_layer_norm": {
                 "scale": _np(sd[t + "final_layer_norm.weight"]),
                 "bias": _np(sd[t + "final_layer_norm.bias"])}}
        for i in range(c.num_hidden_layers):
            h = f"{t}encoder.layers.{i}."
            p[f"layers_{i}"] = {
                "ln_1": {"scale": _np(sd[h + "layer_norm1.weight"]),
                         "bias": _np(sd[h + "layer_norm1.bias"])},
                "ln_2": {"scale": _np(sd[h + "layer_norm2.weight"]),
                         "bias": _np(sd[h + "layer_norm2.bias"])},
                **{name: {"kernel": _t(sd[h + f"self_attn.{name}.weight"]),
                          "bias": _np(sd[h + f"self_attn.{name}.bias"])}
                   for name in ("q_proj", "k_proj", "v_proj", "out_proj")},
                "fc1": {"kernel": _t(sd[h + "mlp.fc1.weight"]),
                        "bias": _np(sd[h + "mlp.fc1.bias"])},
                "fc2": {"kernel": _t(sd[h + "mlp.fc2.weight"]),
                        "bias": _np(sd[h + "mlp.fc2.bias"])},
            }
        return p


class LlamaPolicy(InjectionPolicy):
    """HF LlamaForCausalLM (the reference gained containers/llama.py in
    later snapshots; built natively here). Rotary convention (rotate-half,
    theta = base^(-i/half)) matches models/llama.py exactly, so q/k copy
    straight through."""

    model_type = "llama"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.llama import Llama, LlamaConfig
        c = hf_config
        cfg = LlamaConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_hidden_layers, num_heads=c.num_attention_heads,
            num_kv_heads=getattr(c, "num_key_value_heads",
                                 c.num_attention_heads),
            intermediate_size=c.intermediate_size,
            max_seq_len=c.max_position_embeddings,
            rope_base=getattr(c, "rope_theta", 10000.0),
            rms_eps=c.rms_norm_eps,
            tie_embeddings=getattr(c, "tie_word_embeddings", False),
            dtype=dtype, param_dtype=dtype)
        return Llama(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        p = {"embed_tokens": _np(sd["model.embed_tokens.weight"]),
             "norm": {"scale": _np(sd["model.norm.weight"])}}
        if not getattr(hf_config, "tie_word_embeddings", False):
            p["lm_head"] = {"kernel": _t(sd["lm_head.weight"])}
        for i in range(hf_config.num_hidden_layers):
            h = f"model.layers.{i}."
            p[f"layers_{i}"] = {
                "input_norm": {"scale": _np(sd[h + "input_layernorm.weight"])},
                "post_attn_norm": {
                    "scale": _np(sd[h + "post_attention_layernorm.weight"])},
                "attn": {
                    "wq": {"kernel": _t(sd[h + "self_attn.q_proj.weight"])},
                    "wk": {"kernel": _t(sd[h + "self_attn.k_proj.weight"])},
                    "wv": {"kernel": _t(sd[h + "self_attn.v_proj.weight"])},
                    "wo": {"kernel": _t(sd[h + "self_attn.o_proj.weight"])}},
                "mlp": {
                    "w_gate": {"kernel": _t(sd[h + "mlp.gate_proj.weight"])},
                    "w_up": {"kernel": _t(sd[h + "mlp.up_proj.weight"])},
                    "w_down": {"kernel": _t(sd[h + "mlp.down_proj.weight"])}},
            }
        return p


class AutoTPPolicy(InjectionPolicy):
    """Generic fallback for unknown decoder-only architectures
    (reference ``module_inject/auto_tp.py:13`` — discover the linear
    layout instead of requiring a hand-written container). Recognizes
    the llama-shaped decoder by state-dict structure — per-layer
    q/k/v/o projections, gate/up/down MLP, RMS norms — whatever the HF
    class is (Mistral, and other llama-family derivatives). TP then
    falls out of the native module's logical axes like every policy."""

    model_type = None   # never matched by model_type; from_hf falls back

    _LAYER_KEYS = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                   "self_attn.v_proj.weight", "self_attn.o_proj.weight",
                   "mlp.gate_proj.weight", "mlp.up_proj.weight",
                   "mlp.down_proj.weight", "input_layernorm.weight",
                   "post_attention_layernorm.weight")

    @classmethod
    def discover(cls, sd):
        """Return the decoder prefix (e.g. 'model.') when `sd` has the
        llama-shaped layout, else None."""
        for key in sd:
            if key.endswith("layers.0.self_attn.q_proj.weight"):
                prefix = key[:-len("layers.0.self_attn.q_proj.weight")]
                if all(f"{prefix}layers.0.{k}" in sd
                       for k in cls._LAYER_KEYS) and \
                        f"{prefix}embed_tokens.weight" in sd and \
                        f"{prefix}norm.weight" in sd:
                    return prefix
        return None

    @classmethod
    def ingest(cls, hf_config, sd, dtype=jnp.float32):
        """(module, params) for a discovered llama-shaped decoder."""
        from deepspeed_tpu.models.llama import Llama, LlamaConfig
        prefix = cls.discover(sd)
        if prefix is None:
            raise ValueError(
                "AutoTP fallback: state dict is not a recognizable "
                "llama-shaped decoder (need per-layer "
                "self_attn.{q,k,v,o}_proj + mlp.{gate,up,down}_proj + "
                "input/post_attention layernorms)")
        biased = [k for k in sd
                  if "layers.0." in k and k.endswith("proj.bias")]
        if biased:
            raise ValueError(
                f"AutoTP fallback: biased projections {biased[:3]} need "
                "a hand-written policy (the native llama module is "
                "bias-free)")
        c = hf_config
        # attention-semantics configs the plain llama module cannot
        # honor must fail loudly, not silently diverge
        if getattr(c, "sliding_window", None):
            raise ValueError(
                "AutoTP fallback: config.sliding_window="
                f"{c.sliding_window} — windowed attention needs a "
                "hand-written policy (set sliding_window=None only if "
                "your sequences never exceed the window)")
        if getattr(c, "rope_scaling", None):
            raise ValueError(
                "AutoTP fallback: config.rope_scaling is set — scaled "
                "rope needs a hand-written policy")
        n_layers = 1 + max(
            int(k[len(prefix) + len("layers."):].split(".")[0])
            for k in sd if k.startswith(prefix + "layers."))
        hidden = sd[prefix + "embed_tokens.weight"].shape[1]
        n_heads = getattr(c, "num_attention_heads")
        kv_dim = sd[prefix + "layers.0.self_attn.k_proj.weight"].shape[0]
        head_dim = hidden // n_heads
        tie = getattr(c, "tie_word_embeddings", False) or \
            "lm_head.weight" not in sd
        cfg = LlamaConfig(
            vocab_size=sd[prefix + "embed_tokens.weight"].shape[0],
            hidden_size=hidden,
            num_layers=n_layers, num_heads=n_heads,
            num_kv_heads=kv_dim // head_dim,
            intermediate_size=sd[
                prefix + "layers.0.mlp.gate_proj.weight"].shape[0],
            max_seq_len=getattr(c, "max_position_embeddings", 2048),
            rope_base=getattr(c, "rope_theta", 10000.0),
            rms_eps=getattr(c, "rms_norm_eps", 1e-6),
            tie_embeddings=tie, dtype=dtype, param_dtype=dtype)
        module = Llama(cfg)

        p = {"embed_tokens": _np(sd[prefix + "embed_tokens.weight"]),
             "norm": {"scale": _np(sd[prefix + "norm.weight"])}}
        if not tie:
            p["lm_head"] = {"kernel": _t(sd["lm_head.weight"])}
        for i in range(n_layers):
            h = f"{prefix}layers.{i}."
            p[f"layers_{i}"] = {
                "input_norm": {
                    "scale": _np(sd[h + "input_layernorm.weight"])},
                "post_attn_norm": {
                    "scale":
                        _np(sd[h + "post_attention_layernorm.weight"])},
                "attn": {
                    "wq": {"kernel": _t(sd[h + "self_attn.q_proj.weight"])},
                    "wk": {"kernel": _t(sd[h + "self_attn.k_proj.weight"])},
                    "wv": {"kernel": _t(sd[h + "self_attn.v_proj.weight"])},
                    "wo": {"kernel": _t(sd[h + "self_attn.o_proj.weight"])}},
                "mlp": {
                    "w_gate": {"kernel": _t(sd[h + "mlp.gate_proj.weight"])},
                    "w_up": {"kernel": _t(sd[h + "mlp.up_proj.weight"])},
                    "w_down": {"kernel": _t(sd[h + "mlp.down_proj.weight"])}},
            }
        return module, p


class DistilBertPolicy(InjectionPolicy):
    """HF DistilBertForMaskedLM (reference containers/distil_bert.py:
    HFDistilBertLayerPolicy). BERT encoder minus segment embeddings and
    pooler; MLM head = vocab_transform + vocab_layer_norm + tied
    projector with a bias."""

    model_type = "distilbert"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.bert import Bert, BertConfig
        c = hf_config
        if getattr(c, "sinusoidal_pos_embds", False):
            raise ValueError("sinusoidal_pos_embds DistilBERT variants "
                             "are not supported (learned positions only)")
        cfg = BertConfig(
            vocab_size=c.vocab_size, hidden_size=c.dim,
            num_layers=c.n_layers, num_heads=c.n_heads,
            intermediate_size=c.hidden_dim,
            max_seq_len=c.max_position_embeddings,
            type_vocab_size=0,                # no segment table
            layer_norm_eps=1e-12,
            pre_layer_norm=False,
            activation="gelu_exact" if c.activation == "gelu" else "gelu",
            mlm_bias=True, dtype=dtype, param_dtype=dtype)
        return Bert(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        e = "distilbert.embeddings."
        p = {"word_embeddings": _np(sd[e + "word_embeddings.weight"]),
             "position_embeddings":
                 _np(sd[e + "position_embeddings.weight"]),
             "ln_embed": {"scale": _np(sd[e + "LayerNorm.weight"]),
                          "bias": _np(sd[e + "LayerNorm.bias"])},
             "mlm_transform": {
                 "kernel": _t(sd["vocab_transform.weight"]),
                 "bias": _np(sd["vocab_transform.bias"])},
             "mlm_ln": {"scale": _np(sd["vocab_layer_norm.weight"]),
                        "bias": _np(sd["vocab_layer_norm.bias"])},
             "mlm_decoder_bias": _np(sd["vocab_projector.bias"])}
        for i in range(hf_config.n_layers):
            h = f"distilbert.transformer.layer.{i}."
            qkv_w = np.concatenate(
                [_t(sd[h + f"attention.{n}_lin.weight"])
                 for n in ("q", "k", "v")], axis=1)
            qkv_b = np.concatenate(
                [_np(sd[h + f"attention.{n}_lin.bias"])
                 for n in ("q", "k", "v")])
            p[f"layer_{i}"] = {
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {
                        "kernel": _t(sd[h + "attention.out_lin.weight"]),
                        "bias": _np(sd[h + "attention.out_lin.bias"])}},
                "ln_attn": {
                    "scale": _np(sd[h + "sa_layer_norm.weight"]),
                    "bias": _np(sd[h + "sa_layer_norm.bias"])},
                "ln_mlp": {
                    "scale": _np(sd[h + "output_layer_norm.weight"]),
                    "bias": _np(sd[h + "output_layer_norm.bias"])},
                "fc_in": {"kernel": _t(sd[h + "ffn.lin1.weight"]),
                          "bias": _np(sd[h + "ffn.lin1.bias"])},
                "fc_out": {"kernel": _t(sd[h + "ffn.lin2.weight"]),
                           "bias": _np(sd[h + "ffn.lin2.bias"])},
            }
        return p


class BertPolicy(InjectionPolicy):
    """HF BertForMaskedLM (reference containers/bert.py: HFBertLayerPolicy).
    Post-layernorm encoder; separate q/k/v fuse into the native qkv."""

    model_type = "bert"

    @classmethod
    def build_module(cls, hf_config, dtype=jnp.float32):
        from deepspeed_tpu.models.bert import Bert, BertConfig
        c = hf_config
        cfg = BertConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_layers=c.num_hidden_layers, num_heads=c.num_attention_heads,
            intermediate_size=c.intermediate_size,
            max_seq_len=c.max_position_embeddings,
            type_vocab_size=c.type_vocab_size,
            layer_norm_eps=c.layer_norm_eps,
            pre_layer_norm=False,
            activation="gelu_exact" if c.hidden_act == "gelu" else "gelu",
            mlm_bias=True, dtype=dtype, param_dtype=dtype)
        return Bert(cfg)

    @classmethod
    def convert(cls, hf_config, sd):
        e = "bert.embeddings."
        p = {"word_embeddings": _np(sd[e + "word_embeddings.weight"]),
             "position_embeddings": _np(sd[e + "position_embeddings.weight"]),
             "token_type_embeddings":
                 _np(sd[e + "token_type_embeddings.weight"]),
             "ln_embed": {"scale": _np(sd[e + "LayerNorm.weight"]),
                          "bias": _np(sd[e + "LayerNorm.bias"])},
             "mlm_transform": {
                 "kernel": _t(sd["cls.predictions.transform.dense.weight"]),
                 "bias": _np(sd["cls.predictions.transform.dense.bias"])},
             "mlm_ln": {
                 "scale":
                     _np(sd["cls.predictions.transform.LayerNorm.weight"]),
                 "bias": _np(sd["cls.predictions.transform.LayerNorm.bias"])},
             "mlm_decoder_bias": _np(sd["cls.predictions.bias"])}
        for i in range(hf_config.num_hidden_layers):
            h = f"bert.encoder.layer.{i}."
            qkv_w = np.concatenate(
                [_t(sd[h + f"attention.self.{n}.weight"])
                 for n in ("query", "key", "value")], axis=1)
            qkv_b = np.concatenate(
                [_np(sd[h + f"attention.self.{n}.bias"])
                 for n in ("query", "key", "value")])
            p[f"layer_{i}"] = {
                "attn": {
                    "qkv": {"kernel": qkv_w, "bias": qkv_b},
                    "proj": {
                        "kernel": _t(sd[h + "attention.output.dense.weight"]),
                        "bias": _np(sd[h + "attention.output.dense.bias"])}},
                "ln_attn": {
                    "scale": _np(sd[h + "attention.output.LayerNorm.weight"]),
                    "bias": _np(sd[h + "attention.output.LayerNorm.bias"])},
                "ln_mlp": {"scale": _np(sd[h + "output.LayerNorm.weight"]),
                           "bias": _np(sd[h + "output.LayerNorm.bias"])},
                "fc_in": {"kernel": _t(sd[h + "intermediate.dense.weight"]),
                          "bias": _np(sd[h + "intermediate.dense.bias"])},
                "fc_out": {"kernel": _t(sd[h + "output.dense.weight"]),
                           "bias": _np(sd[h + "output.dense.bias"])},
            }
        return p
