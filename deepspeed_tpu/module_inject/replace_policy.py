"""Policy registry (reference ``module_inject/replace_policy.py`` —
``replace_policies``/``generic_policies`` lists)."""

from deepspeed_tpu.module_inject.policy import (BertPolicy, BloomPolicy,
                                                GPT2Policy, GPTJPolicy,
                                                GPTNeoXPolicy, LlamaPolicy,
                                                OPTPolicy)

POLICIES = [GPT2Policy, OPTPolicy, BloomPolicy, GPTJPolicy, GPTNeoXPolicy,
            LlamaPolicy, BertPolicy]


def policy_for(hf_config):
    for pol in POLICIES:
        if pol.matches(hf_config):
            return pol
    raise ValueError(
        f"no ingestion policy for model_type="
        f"{getattr(hf_config, 'model_type', None)!r}; supported: "
        f"{[p.model_type for p in POLICIES]}")
