"""Policy registry (reference ``module_inject/replace_policy.py`` —
``replace_policies``/``generic_policies`` lists)."""

from deepspeed_tpu.module_inject.policy import (AutoTPPolicy, BertPolicy,
                                                CLIPPolicy,
                                                BloomPolicy,
                                                DistilBertPolicy, GPT2Policy,
                                                GPTJPolicy, GPTNeoPolicy,
                                                GPTNeoXPolicy,
                                                LlamaPolicy,
                                                MegatronGPT2Policy,
                                                MegatronGPTMoEPolicy,
                                                OPTPolicy)

POLICIES = [GPT2Policy, OPTPolicy, BloomPolicy, GPTJPolicy, GPTNeoPolicy,
            CLIPPolicy,
            GPTNeoXPolicy, LlamaPolicy, MegatronGPTMoEPolicy,
            MegatronGPT2Policy, BertPolicy,
            DistilBertPolicy]


def policy_for(hf_config):
    for pol in POLICIES:
        if pol.matches(hf_config):
            return pol
    raise ValueError(
        f"no ingestion policy for model_type="
        f"{getattr(hf_config, 'model_type', None)!r}; supported: "
        f"{[p.model_type for p in POLICIES]} "
        f"(+ the AutoTP structural fallback for llama-shaped decoders)")
