"""Multi-node runners: build the command that starts ``launch.py`` on
every host.

Reference: ``deepspeed/launcher/multinode_runner.py:18-256`` (PDSH /
OpenMPI / MPICH / SLURM / MVAPICH). On TPU pods the per-host process model
is identical (ssh/pdsh into each worker, one launcher per host); a
GcloudRunner covers `gcloud compute tpus tpu-vm ssh --worker=all`, the
idiomatic pod fan-out.
"""

import os
import shlex
import sys


class MultiNodeRunner:
    def __init__(self, args, world_info):
        """world_info: {hostname: num_workers} in rank order."""
        self.args = args
        self.world_info = world_info
        self.user_arguments = list(getattr(args, "user_args", []) or [])
        self.user_script = args.user_script
        self.exports = {}

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    @property
    def name(self):
        raise NotImplementedError

    def backend_exists(self):
        raise NotImplementedError

    def get_cmd(self, environment, active_resources):
        raise NotImplementedError

    def _launch_args(self, node_rank, num_workers):
        a = self.args
        return ["-m", "deepspeed_tpu.launcher.launch",
                f"--node_rank={node_rank}",
                f"--num_nodes={len(self.world_info)}",
                f"--num_workers={num_workers}",
                f"--master_addr={a.master_addr}",
                f"--master_port={a.master_port}"]


class PDSHRunner(MultiNodeRunner):
    @property
    def name(self):
        return "pdsh"

    def backend_exists(self):
        from shutil import which
        return which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        # %n expands to the pdsh node-number = node rank (reference
        # multinode_runner.py PDSH '%n' trick)
        workers = next(iter(active_resources.values()))
        cmd = (exports + f"cd {os.path.abspath('.')}; "
               + " ".join([sys.executable]
                          + self._launch_args("%n", workers)
                          + [self.user_script] + self.user_arguments))
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, cmd], environment


class GcloudRunner(MultiNodeRunner):
    """TPU-pod fan-out via `gcloud compute tpus tpu-vm ssh --worker=all`."""

    @property
    def name(self):
        return "gcloud"

    def backend_exists(self):
        from shutil import which
        return which("gcloud") is not None

    def get_cmd(self, environment, active_resources):
        a = self.args
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        workers = next(iter(active_resources.values()))
        inner = (exports + " ".join(
            [sys.executable] + self._launch_args("$TPU_WORKER_ID", workers)
            + [self.user_script] + self.user_arguments))
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                a.tpu_name, "--worker=all",
                f"--command={inner}"], dict(environment)


class SlurmRunner(MultiNodeRunner):
    @property
    def name(self):
        return "slurm"

    def backend_exists(self):
        from shutil import which
        return which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        workers = next(iter(active_resources.values()))
        srun = ["srun", "-N", str(total_nodes),
                "--ntasks-per-node", "1"]
        exports = []
        for k, v in self.exports.items():
            exports += ["--export", f"{k}={v}"]
        cmd = srun + exports + [sys.executable] + \
            self._launch_args("$SLURM_NODEID", workers) + \
            [self.user_script] + self.user_arguments
        return cmd, dict(environment)
