"""`deepspeed_tpu` CLI: resource parsing + multi-process launch.

Reference: ``deepspeed/launcher/runner.py`` — hostfile parse (:179),
--include/--exclude filtering (:234-331), runner selection + exec (:367).
Single-node runs exec ``launcher/launch.py`` directly; multi-node builds a
pdsh/gcloud/slurm command (multinode_runner.py).
"""

import argparse
import os
import re
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    p = argparse.ArgumentParser(
        description="deepspeed_tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE,
                   help="hostname slots=N per line")
    p.add_argument("-i", "--include", default="",
                   help='e.g. "host1,host2:0,2"')
    p.add_argument("-e", "--exclude", default="", help="inverse of include")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--num_workers", "--num_gpus", type=int, default=-1,
                   dest="num_workers", help="processes per node")
    p.add_argument("--master_addr", default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--launcher", default="pdsh",
                   choices=("pdsh", "gcloud", "slurm"))
    p.add_argument("--tpu_name", default=os.environ.get("TPU_NAME", ""),
                   help="gcloud launcher: TPU pod name")
    p.add_argument("--force_cpu_devices", type=int, default=0,
                   help="virtual CPU devices per process (CI/testing)")
    p.add_argument("--autotuning", default="", choices=("", "tune", "run"))
    p.add_argument("user_script", help="training script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(args)


def fetch_hostfile(path):
    """Parse 'hostname slots=N' lines -> {hostname: N} (reference :179)."""
    if not os.path.isfile(path):
        return None
    resources = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)$", line)
            if m is None:
                raise ValueError(f"bad hostfile line: {line!r}")
            host, slots = m.group(1), int(m.group(2))
            if host in resources:
                raise ValueError(f"duplicate host {host} in hostfile")
            resources[host] = slots
    if not resources:
        raise ValueError(f"hostfile {path} is empty")
    return resources


def _parse_filter(spec):
    """'host1@host2:0,2' -> {host1: None, host2: [0, 2]}. Hosts separated
    by '@', slot lists by ',' (reference uses the same two-level split)."""
    out = {}
    for part in spec.split("@"):
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":", 1)
            out[host] = [int(s) for s in slots.split(",") if s != ""]
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resources, include, exclude):
    """Filter {host: slots} by include/exclude specs (reference :234)."""
    assert not (include and exclude), \
        "--include and --exclude are mutually exclusive"
    active = {}
    if include:
        spec = _parse_filter(include)
        for host, idx in spec.items():
            assert host in resources, f"unknown host {host}"
            active[host] = len(idx) if idx else resources[host]
    elif exclude:
        spec = _parse_filter(exclude)
        for host, slots in resources.items():
            if host not in spec:
                active[host] = slots
            elif spec[host]:
                remaining = slots - len(spec[host])
                if remaining > 0:
                    active[host] = remaining
    else:
        active = dict(resources)
    return active


def main(args=None):
    args = parse_args(args)
    resources = fetch_hostfile(args.hostfile)

    if resources is None or len(resources) <= 1:
        # single node: exec launch.py directly (reference :367 local path)
        num_workers = args.num_workers if args.num_workers > 0 else 1
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
               "--node_rank=0", "--num_nodes=1",
               f"--num_workers={num_workers}",
               f"--master_addr={args.master_addr}",
               f"--master_port={args.master_port}"]
        if args.force_cpu_devices:
            cmd.append(f"--force_cpu_devices={args.force_cpu_devices}")
        cmd += [args.user_script] + args.user_args
        logger.info(f"cmd: {' '.join(cmd)}")
        return subprocess.call(cmd)

    active = parse_inclusion_exclusion(resources, args.include, args.exclude)
    if args.num_nodes > 0:
        active = dict(list(active.items())[:args.num_nodes])
    from deepspeed_tpu.launcher.multinode_runner import (GcloudRunner,
                                                         PDSHRunner,
                                                         SlurmRunner)
    cls = {"pdsh": PDSHRunner, "gcloud": GcloudRunner,
           "slurm": SlurmRunner}[args.launcher]
    runner = cls(args, active)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {runner.name} not available")
    env = os.environ.copy()
    cmd, env = runner.get_cmd(env, active)
    logger.info(f"cmd: {' '.join(cmd)}")
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    sys.exit(main())
