"""Per-node process launcher.

Reference: ``deepspeed/launcher/launch.py:120`` — decodes the world info,
spawns one subprocess per local rank with RANK/LOCAL_RANK/WORLD_SIZE/
MASTER_ADDR env, installs signal handlers that terminate the whole tree.

TPU mapping: one process per *host* is the norm (a host owns all its
chips), so ``--num_workers`` counts processes on this node — >1 is the
CPU-CI configuration where each process gets a virtual device slice. Env
contract consumed by ``comm.init_distributed``:
COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID (plus RANK/LOCAL_RANK/
WORLD_SIZE mirrors for reference-style client code).
"""

import argparse
import os
import signal
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    p = argparse.ArgumentParser(description="per-node launcher")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--num_nodes", type=int, default=1)
    p.add_argument("--num_workers", type=int, default=1,
                   help="processes to spawn on this node")
    p.add_argument("--master_addr", default="127.0.0.1")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--force_cpu_devices", type=int, default=0,
                   help="virtual CPU devices per process (CI)")
    p.add_argument("--elastic", action="store_true",
                   help="supervise workers with restart-on-failure "
                        "(reference elastic_agent.py)")
    p.add_argument("--max_elastic_restarts", type=int, default=3)
    p.add_argument("--rdzv_port", type=int, default=None,
                   help="multi-node elastic: the node-0 agent's "
                        "rendezvous-store port (all agents connect to "
                        "master_addr:rdzv_port)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(args)


def main(args=None):
    args = parse_args(args)
    if args.elastic:
        from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
        agent = DSElasticAgent(
            args.training_script, args.training_script_args,
            num_workers=args.num_workers, num_nodes=args.num_nodes,
            node_rank=args.node_rank, master_addr=args.master_addr,
            master_port=args.master_port,
            max_restarts=args.max_elastic_restarts,
            force_cpu_devices=args.force_cpu_devices,
            rdzv_port=args.rdzv_port)
        sys.exit(agent.run())
    world_size = args.num_nodes * args.num_workers
    procs = []

    def terminate(signum=None, frame=None):
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        if signum is not None:
            sys.exit(128 + signum)

    signal.signal(signal.SIGINT, terminate)
    signal.signal(signal.SIGTERM, terminate)

    for local_rank in range(args.num_workers):
        rank = args.node_rank * args.num_workers + local_rank
        env = os.environ.copy()
        env.update({
            "COORDINATOR_ADDRESS": f"{args.master_addr}:{args.master_port}",
            "NUM_PROCESSES": str(world_size),
            "PROCESS_ID": str(rank),
            "RANK": str(rank),
            "LOCAL_RANK": str(local_rank),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": args.master_addr,
            "MASTER_PORT": str(args.master_port),
        })
        if args.force_cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count="
                                f"{args.force_cpu_devices}")
        cmd = [sys.executable, args.training_script] + \
            args.training_script_args
        logger.info(f"launch rank {rank}: {' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    for proc in procs:
        proc.wait()
        if proc.returncode != 0:
            rc = proc.returncode
            terminate()
            break
    sys.exit(rc)


if __name__ == "__main__":
    main()
