"""CLIP text encoder (the diffusers serving stack's text tower).

Reference: ``deepspeed/module_inject/containers/clip.py``
(HFCLIPLayerPolicy injecting DeepSpeedGPTInference into
``transformers`` CLIPEncoderLayer) — the text half of the stable
-diffusion ``generic_injection`` path (replace_module.py:182).

TPU form: a native flax module with the exact HF CLIPTextModel
numerics — causal text attention, pre-LN blocks, quick_gelu — so
ingestion (module_inject.policy.CLIPPolicy) is a pure weight relayout
and attention routes through the same QDense/flash machinery as every
other family (int8 serving and sharding rules apply unchanged).
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.ops.attention.reference import mha_reference


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_layers: int = 12
    num_heads: int = 8
    max_seq_len: int = 77
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def quick_gelu(x):
    return x * nn.sigmoid(1.702 * x)


def _dense(cfg, features, axes, name):
    from deepspeed_tpu.ops.quant.qdense import QDense
    return QDense(features, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                  kernel_init=nn.with_partitioning(
                      nn.initializers.normal(0.02), axes), name=name)


class CLIPEncoderLayer(nn.Module):
    cfg: CLIPTextConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, l, _ = x.shape
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_1")(x)
        q = _dense(cfg, cfg.hidden_size, ("embed", "kv"), "q_proj")(h)
        k = _dense(cfg, cfg.hidden_size, ("embed", "kv"), "k_proj")(h)
        v = _dense(cfg, cfg.hidden_size, ("embed", "kv"), "v_proj")(h)
        q = q.reshape(b, l, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, l, cfg.num_heads, cfg.head_dim)
        v = v.reshape(b, l, cfg.num_heads, cfg.head_dim)
        o = mha_reference(q, k, v, causal=True)   # CLIP text is causal
        o = o.reshape(b, l, cfg.hidden_size)
        x = x + _dense(cfg, cfg.hidden_size, ("heads", "embed"),
                       "out_proj")(o)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_2")(x)
        h = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "fc1")(h)
        h = quick_gelu(h)
        h = _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "fc2")(h)
        return x + h


class CLIPText(nn.Module):
    """Returns last_hidden_state [b, l, hidden] (HF CLIPTextModel
    contract; the pooled eot-token output is a gather the caller owns)."""
    cfg: CLIPTextConfig

    qtensor_params = True   # QDense consumes QTensor kernels (int8)

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.cfg
        b, l = input_ids.shape
        tok = self.param(
            "token_embedding",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param(
            "position_embedding",
            nn.with_partitioning(nn.initializers.normal(0.01),
                                 ("seq", "embed")),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
        tok_v = tok.value if hasattr(tok, "value") else tok
        pos_v = pos.value if hasattr(pos, "value") else pos
        x = tok_v.astype(cfg.dtype)[input_ids] + \
            pos_v.astype(cfg.dtype)[None, :l]
        for i in range(cfg.num_layers):
            x = CLIPEncoderLayer(cfg, name=f"layers_{i}")(x)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                            name="final_layer_norm")(x)
