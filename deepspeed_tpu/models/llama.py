"""Llama-family causal transformer (RMSNorm, RoPE, SwiGLU, GQA), TPU-first.

This is the flagship for the north-star ZeRO-3 target (BASELINE.json:
"Llama-2-70B on v5p-256") and the inference stack. Same logical-axis
partitioning scheme as models/gpt2.py; reference parity targets
deepspeed's Llama policy/containers (module_inject/containers/llama.py
in later snapshots) re-designed as a native flax model.

KV-cache decode is built in: ``__call__(ids, positions=..., cache=...)``
returns ``(logits, new_cache)`` — the cache is a plain pytree updated with
``lax.dynamic_update_slice`` so single-token decode jits to the
``softmax_context`` equivalent (reference csrc/transformer/inference).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.ops.attention.reference import (apply_rotary_emb,
                                                   decode_attention_reference,
                                                   mha_reference)


@dataclasses.dataclass(unsafe_hash=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32            # < num_heads => GQA
    intermediate_size: int = 11008
    max_seq_len: int = 4096
    rope_base: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "auto"
    tie_embeddings: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.with_partitioning(
            nn.initializers.ones_init(), ("embed",)), (x.shape[-1],),
            jnp.float32)
        scale = scale.value if hasattr(scale, "value") else scale
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        y = x.astype(jnp.float32) * lax.rsqrt(var + self.eps)
        return (y * scale).astype(self.dtype)


def _proj(cfg, features, axes, name):
    from deepspeed_tpu.ops.quant.qdense import QDense
    return QDense(features, use_bias=False, dtype=cfg.dtype,
                  param_dtype=cfg.param_dtype,
                  kernel_init=nn.with_partitioning(
                      nn.initializers.normal(0.02), axes),
                  name=name)


from deepspeed_tpu.ops.attention.decode import _repeat_kv  # GQA expansion


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, cache=None):
        cfg = self.cfg
        b, l, _ = x.shape
        h, kv_h, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        # multi-tenant serving: per-slot LoRA deltas ride the paged
        # cache as a stacked side input (models/lora.py); absent for
        # base-only traffic, so that path's trace is unchanged
        ad = cache.get("adapters") if cache is not None else None
        if ad is not None:
            from deepspeed_tpu.models.lora import adapter_rows, lora_delta
            ad_rows = adapter_rows(ad, cache)
        q = _proj(cfg, h * d, ("embed", "heads"), "wq")(x)
        k = _proj(cfg, kv_h * d, ("embed", "kv"), "wk")(x)
        v = _proj(cfg, kv_h * d, ("embed", "kv"), "wv")(x)
        if ad is not None:
            if "wq" in ad:
                q = q + lora_delta(x, ad["wq"], ad_rows, ad["scale"])
            if "wk" in ad:
                k = k + lora_delta(x, ad["wk"], ad_rows, ad["scale"])
            if "wv" in ad:
                v = v + lora_delta(x, ad["wv"], ad_rows, ad["scale"])
        q = q.reshape(b, l, h, d)
        k = k.reshape(b, l, kv_h, d)
        v = v.reshape(b, l, kv_h, d)
        q = apply_rotary_emb(q, positions, base=cfg.rope_base)
        k = apply_rotary_emb(k, positions, base=cfg.rope_base)

        new_cache = None
        if cache is not None and "k_pages" in cache:
            # paged serving path — same contract as models/gpt2.py:
            # pools [num_pages, page_size, kv_h, d] shared via a per-slot
            # page table; GQA pools stay grouped end to end
            from deepspeed_tpu.ops.attention import (decode_attention,
                                                     paged_decode_attention)
            from deepspeed_tpu.ops.quant.kv import (paged_gather,
                                                    paged_write)
            k_pages, v_pages = cache["k_pages"], cache["v_pages"]
            num_pages, ps = k_pages.shape[0], k_pages.shape[1]
            pt = cache["page_table"]
            max_len = pt.shape[1] * ps
            if "slot" in cache:          # chunked prefill (b == 1)
                # the chunk starts at lengths[slot] — a prefix-cache
                # hit seeds it to the cached (possibly mid-page)
                # boundary: rotary offsets follow the positions array,
                # writes never touch shared read-only pages below the
                # boundary, and the copy-on-write tail page's stale
                # region is overwritten-before-gather or masked.
                # paged_write quantizes to int8/fp8 pools (with parallel
                # per-row scale pools) when the cache carries them;
                # float pools take the byte-identical legacy path
                slot = cache["slot"]
                pos = positions[0]
                valid = jnp.arange(l) < cache["n_valid"]
                page_ids = jnp.where(valid, pt[slot, pos // ps], num_pages)
                pools_out = paged_write(cache, page_ids, pos % ps,
                                        k[0], v[0])
                seq_ax = cache.get("seq_axis")
                if seq_ax is not None:
                    # sequence-parallel prefill (static trace-time
                    # marker, same contract as models/gpt2.py): the
                    # write above already landed the chunk's KV in the
                    # standard pool; attention runs distributed over
                    # the sequence axis against the pool gather.  The
                    # distributed transports take full-head k/v, so GQA
                    # pools expand to h heads HERE only — the pool
                    # itself stays grouped
                    from deepspeed_tpu import comm as dist
                    from deepspeed_tpu.sequence.prefill import (
                        paged_prefill_attention)
                    k_pref, v_pref = paged_gather(pools_out,
                                                  pt[slot][None], q.dtype)
                    rep = h // kv_h
                    out = paged_prefill_attention(
                        q, _repeat_kv(k, rep), _repeat_kv(v, rep),
                        _repeat_kv(k_pref, rep), _repeat_kv(v_pref, rep),
                        positions[0, 0], dist.get_mesh(), axis=seq_ax,
                        impl=cache["seq_impl"])
                else:
                    k_slot, v_slot = paged_gather(pools_out, pt[slot][None],
                                                  q.dtype)
                    k_pos = jnp.arange(max_len)
                    mask = k_pos[None, None, :] <= positions[:, :, None]
                    bias = jnp.where(mask, 0.0,
                                     jnp.finfo(jnp.float32).min)[:, None]
                    out = decode_attention(q, k_slot, v_slot, bias=bias)
            elif "widths" in cache:
                # teacher-forced multi-token verify (speculative decode):
                # b == slots, l == K+1 candidate tokens; column j of
                # slot s writes position lengths[s] + j when
                # j < widths[s] (0 for inactive slots) and attends
                # causally through the page table in ONE batched
                # forward — same contract as models/gpt2.py. Rotary
                # offsets ride the positions array; GQA pools stay
                # grouped through the gather + decode_attention path.
                widths = cache["widths"]
                pos = positions                          # [slots, l]
                write = jnp.arange(l)[None, :] < widths[:, None]
                page_ids = jnp.where(
                    write, pt[jnp.arange(b)[:, None], pos // ps], num_pages)
                pools_out = paged_write(cache, page_ids, pos % ps, k, v)
                k_slot, v_slot = paged_gather(pools_out, pt, q.dtype)
                k_pos = jnp.arange(max_len)
                mask = k_pos[None, None, :] <= pos[:, :, None]
                bias = jnp.where(mask, 0.0,
                                 jnp.finfo(jnp.float32).min)[:, None]
                out = decode_attention(q, k_slot, v_slot, bias=bias)
            else:                        # continuous-batch decode (l == 1)
                # paged_decode_attention owns the kernel dispatch: GQA
                # pools run the per-kv-head BlockSpec kernel grouped
                # (never expanded), and a multi-device mesh runs it
                # per-shard under shard_map — each device gets its kv
                # shard's q-head group; this call site is topology-blind
                active = cache["active"]
                pos = positions[:, 0]
                page_ids = jnp.where(active,
                                     pt[jnp.arange(b), pos // ps], num_pages)
                pools_out = paged_write(cache, page_ids, pos % ps,
                                        k[:, 0], v[:, 0])
                out = paged_decode_attention(
                    q, pools_out["k_pages"], pools_out["v_pages"], pt,
                    pos, k_scale=pools_out.get("k_scale"),
                    v_scale=pools_out.get("v_scale"))
            # multi-chip serving: pin the pools' kv-head sharding on the
            # updated arrays so GSPMD keeps the scatter/gather split
            # over the `model` axis — GQA pools shard num_kv_heads, so
            # the `model` size must divide it (engine-validated); the
            # quantized scale pools share the payload's axis family
            from deepspeed_tpu.serving.sharding import constrain_kv_pages
            new_cache = {name: constrain_kv_pages(arr)
                         for name, arr in pools_out.items()}
        elif cache is not None:
            # decode: append k/v at cache["index"], attend over valid prefix
            k_cache = lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, cache["index"], 0, 0))
            v_cache = lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, cache["index"], 0, 0))
            new_cache = {"k": k_cache, "v": v_cache,
                         "index": cache["index"] + l}
            # attend over the whole cache buffer with a positional mask:
            # slot j is visible to query at absolute position p iff j <= p
            # (cache["index"] is traced, so no dynamic slicing). Single-token
            # steps hit the Pallas softmax_context kernel; GQA caches are
            # consumed grouped, never expanded.
            max_len = k_cache.shape[1]
            k_pos = jnp.arange(max_len)
            mask = k_pos[None, None, :] <= positions[:, :, None]  # [b,l,max]
            bias = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
            from deepspeed_tpu.ops.attention import decode_attention
            out = decode_attention(q, k_cache, v_cache, bias=bias[:, None])

        else:
            k_full = _repeat_kv(k, h // kv_h)
            v_full = _repeat_kv(v, h // kv_h)
            impl = cfg.attn_impl
            if impl == "auto":
                impl = "flash" if (jax.default_backend() == "tpu" and
                                   l % 128 == 0) else "reference"
            if impl == "flash":
                from deepspeed_tpu.ops.attention import flash_attention
                out = flash_attention(q, k_full, v_full, causal=True)
            elif impl in ("ring", "ulysses"):
                from deepspeed_tpu import comm as dist
                from deepspeed_tpu.sequence import DistributedAttention
                mesh = dist.get_mesh()
                assert mesh is not None and \
                    mesh.shape.get("sequence", 1) > 1, \
                    f"attn_impl={impl} needs a sequence mesh axis > 1"
                out = DistributedAttention(mesh, impl=impl)(q, k_full, v_full)
            else:
                out = mha_reference(q, k_full, v_full, causal=True)

        out = out.reshape(b, l, h * d)
        wo_in = out
        out = _proj(cfg, cfg.hidden_size, ("heads", "embed"), "wo")(wo_in)
        if ad is not None and "wo" in ad:
            out = out + lora_delta(wo_in, ad["wo"], ad_rows, ad["scale"])
        return out, new_cache


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, adapters=None, ad_rows=None):
        cfg = self.cfg
        gate = _proj(cfg, cfg.intermediate_size, ("embed", "mlp"), "w_gate")(x)
        up = _proj(cfg, cfg.intermediate_size, ("embed", "mlp"), "w_up")(x)
        if adapters is not None:
            from deepspeed_tpu.models.lora import lora_delta
            if "w_gate" in adapters:
                gate = gate + lora_delta(x, adapters["w_gate"], ad_rows,
                                         adapters["scale"])
            if "w_up" in adapters:
                up = up + lora_delta(x, adapters["w_up"], ad_rows,
                                     adapters["scale"])
        h = nn.silu(gate) * up
        down = _proj(cfg, cfg.hidden_size, ("mlp", "embed"), "w_down")(h)
        if adapters is not None and "w_down" in adapters:
            from deepspeed_tpu.models.lora import lora_delta
            down = down + lora_delta(h, adapters["w_down"], ad_rows,
                                     adapters["scale"])
        return down


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, cache=None):
        cfg = self.cfg
        ad = cache.get("adapters") if cache is not None else None
        ad_rows = None
        if ad is not None:
            from deepspeed_tpu.models.lora import adapter_rows
            ad_rows = adapter_rows(ad, cache)
        attn_out, new_cache = LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.dtype, name="input_norm")(x),
            positions, cache)
        x = x + attn_out
        x = x + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_eps, cfg.dtype, name="post_attn_norm")(x),
            ad, ad_rows)
        return x, new_cache


class Llama(nn.Module):
    """Returns logits [b, l, vocab]; with ``cache`` returns (logits, cache)."""
    cfg: LlamaConfig

    qtensor_params = True   # QDense consumes QTensor kernels (int8 serving)

    @nn.compact
    def __call__(self, input_ids, deterministic=True, positions=None,
                 cache=None):
        cfg = self.cfg
        b, l = input_ids.shape
        paged = cache is not None and "page_table" in cache
        if positions is None:
            if paged:
                lens = cache["lengths"]
                if "slot" in cache:      # chunked prefill (b == 1)
                    positions = (lens[cache["slot"]] +
                                 jnp.arange(l))[None, :]
                elif "widths" in cache:  # teacher-forced verify (l == K+1)
                    positions = lens[:, None] + jnp.arange(l)[None, :]
                else:                    # continuous-batch decode (l == 1)
                    positions = lens[:, None]
                positions = jnp.broadcast_to(positions, (b, l))
            elif cache is not None:
                start = cache["layers"][0]["index"]
                positions = start + jnp.arange(l)[None, :]
                positions = jnp.broadcast_to(positions, (b, l))
            else:
                positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))

        embed = self.param("embed_tokens", nn.with_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        embed_v = embed.value if hasattr(embed, "value") else embed
        x = embed_v.astype(cfg.dtype)[input_ids]

        block = LlamaBlock
        if cfg.remat and cache is None:
            # cache=None is an empty pytree, safe through remat
            block = nn.remat(LlamaBlock, prevent_cse=False)
        new_layer_caches = []
        for i in range(cfg.num_layers):
            layer_cache = cache["layers"][i] if cache is not None else None
            if paged:
                layer_cache = dict(layer_cache,
                                   page_table=cache["page_table"])
                for key in ("slot", "n_valid", "active", "widths",
                            "seq_axis", "seq_impl"):
                    if key in cache:
                        layer_cache[key] = cache[key]
                if "adapters" in cache:
                    from deepspeed_tpu.models.lora import layer_adapters
                    layer_cache["adapters"] = layer_adapters(cache, i)
            x, new_c = block(cfg, name=f"layers_{i}")(x, positions,
                                                      layer_cache)
            new_layer_caches.append(new_c)

        if paged and "slot" in cache:
            # chunked prefill consumes ONLY the boundary row — skip the
            # full-vocab head for the chunk's other positions
            x = lax.dynamic_slice_in_dim(x, cache["n_valid"] - 1, 1, axis=1)
        x = RMSNorm(cfg.rms_eps, cfg.dtype, name="norm")(x)
        if cfg.tie_embeddings:
            logits = jnp.einsum("ble,ve->blv", x, embed_v.astype(cfg.dtype))
        else:
            logits = _proj(cfg, cfg.vocab_size, ("embed", "vocab"),
                           "lm_head")(x)
        if paged:
            if "slot" in cache:
                lengths = cache["lengths"].at[cache["slot"]].add(
                    cache["n_valid"])
            elif "widths" in cache:
                # verify: widths columns written per slot; the engine's
                # verify primitive rewinds this after acceptance
                lengths = cache["lengths"] + cache["widths"]
            else:
                lengths = cache["lengths"] + \
                    cache["active"].astype(jnp.int32)
            return logits, dict(cache, lengths=lengths,
                                layers=new_layer_caches)
        if cache is not None:
            return logits, {"layers": new_layer_caches}
        return logits


def init_kv_cache(cfg: LlamaConfig, batch_size, max_len=None,
                  dtype=jnp.bfloat16):
    """Empty KV cache pytree (reference inference_context.h workspace)."""
    max_len = max_len or cfg.max_seq_len
    layer = lambda: {
        "k": jnp.zeros((batch_size, max_len, cfg.num_kv_heads, cfg.head_dim),
                       dtype),
        "v": jnp.zeros((batch_size, max_len, cfg.num_kv_heads, cfg.head_dim),
                       dtype),
        "index": jnp.int32(0),
    }
    return {"layers": [layer() for _ in range(cfg.num_layers)]}


def init_paged_kv_cache(cfg: LlamaConfig, num_pages, page_size,
                        dtype=jnp.bfloat16):
    """Per-layer paged KV pools (serving/ subsystem) — GQA pools are
    sized to num_kv_heads and stay grouped through the paged kernel.
    ``dtype`` may be a quantized kv-dtype name ("int8"/"fp8"): int8/fp8
    payload pools plus parallel per-row f32 scale pools
    (ops/quant/kv.py storage contract)."""
    from deepspeed_tpu.ops.quant.kv import paged_pool_layer
    layer = lambda: paged_pool_layer(num_pages, page_size,
                                     cfg.num_kv_heads, cfg.head_dim,
                                     dtype)
    return {"layers": [layer() for _ in range(cfg.num_layers)]}


def llama_tiny(**overrides):
    """Test-fixture scale (reference tests/unit/simple_model.py spirit)."""
    kwargs = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, intermediate_size=128, max_seq_len=128)
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


def llama2_7b(**overrides):
    return LlamaConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                       num_heads=32, num_kv_heads=32, intermediate_size=11008,
                       max_seq_len=4096, **overrides)


def llama2_70b(**overrides):
    return LlamaConfig(vocab_size=32000, hidden_size=8192, num_layers=80,
                       num_heads=64, num_kv_heads=8, intermediate_size=28672,
                       max_seq_len=4096, **overrides)
