"""Per-slot batched LoRA deltas for the paged serving path (S-LoRA /
Punica shape).

The serving tier holds N adapters' low-rank factors as STACKED device
arrays — ``a [n, in, rank]`` / ``b [n, rank, out]`` per injected
projection, rank zero-padded to a power-of-two bucket — and threads a
per-slot ``adapter_ids`` array through the fused decode scan.  Each
batch row gathers ITS adapter's factors and adds
``scale[id] * (x @ A[id] @ B[id])`` to the base projection:

* the gather + two batched einsums are shape-fixed by (slots, rank
  bucket), so adapter churn never changes the jit signature — one
  compiled signature per (horizon, rank-bucket), exactly like the page
  pool fixes the KV signature;
* ``adapter_id == -1`` multiplies the delta by 0.0 — base-only rows in
  a mixed batch stay token-exact vs base-only serving (the whole
  ``adapters`` side input is absent for tenancy-off traffic, which
  keeps that path byte-identical);
* zero-padding the rank adds exact zero columns/rows to A/B, so a
  rank-5 adapter served in an 8-bucket produces bit-identical deltas
  to its unpadded math.

The weight dict a layer sees (``cache["adapters"]`` after the model
top-level fans it out per layer) is::

    {"ids":   int32 [num_slots]          (-1 = no adapter),
     "scale": float32 [n],
     <target>: {"a": [n, in, r], "b": [n, r, out]}, ...}

Target names follow the model's projection module names (gpt2:
``qkv``/``proj``/``fc_in``/``fc_out``; llama: ``wq``/``wk``/``wv``/
``wo``/``w_gate``/``w_up``/``w_down``).  A missing target is simply
not injected — adapters may cover any subset.
"""

import jax.numpy as jnp


def adapter_rows(adapters, cache):
    """Per-batch-row adapter ids for the current paged-cache marker.

    Chunked prefill runs b == 1 for ONE slot (the ``slot`` marker), so
    the row id is that slot's entry; decode (l == 1) and teacher-forced
    verify (``widths`` marker) run b == num_slots with one row per
    slot, so the ids array maps through unchanged."""
    ids = adapters["ids"]
    if "slot" in cache:
        return ids[cache["slot"]][None]
    return ids


def lora_delta(x, pack, rows, scale):
    """Batched per-row LoRA delta: ``scale[rows] * (x @ A[rows] @
    B[rows])``, 0.0 where ``rows < 0``.

    ``x`` is [b, ..., in]; ``pack`` holds the stacked factors
    ``{"a": [n, in, r], "b": [n, r, out]}``; ``rows`` is int32 [b].
    Each batch row's matmul chain is independent of the other rows, so
    a slot's delta is bit-identical whether it shares the batch with 0
    or 7 other adapters — the mixed-batch token-exactness oracle rests
    on this."""
    safe = jnp.maximum(rows, 0)
    a = jnp.take(pack["a"], safe, axis=0)                # [b, in, r]
    bm = jnp.take(pack["b"], safe, axis=0)               # [b, r, out]
    coef = jnp.where(rows >= 0, jnp.take(scale, safe), 0.0)
    h = jnp.einsum("b...i,bir->b...r", x, a.astype(x.dtype))
    d = jnp.einsum("b...r,bro->b...o", h, bm.astype(x.dtype))
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return d * coef.reshape(shape).astype(x.dtype)


def layer_adapters(cache, layer_idx):
    """Slice the model-level ``cache["adapters"]`` side input down to
    ONE layer's injection dict (ids/scale shared, per-layer factor
    stacks) — the per-layer cache fan-out in gpt2/llama calls this."""
    ad = cache.get("adapters") if cache is not None else None
    if ad is None:
        return None
    return dict(ad["layers"][layer_idx], ids=ad["ids"], scale=ad["scale"])


def lora_targets(cfg):
    """(in_dim, out_dim, sharded_dim) per injectable projection for a
    model config — the AdapterStore validates adapter checkpoints and
    lays out the stacked device arrays against this table.
    ``sharded_dim`` names which factor dimension sits on the ``model``
    mesh axis in the base kernel ("out" for column-parallel, "in" for
    row-parallel) — the store mirrors that placement when it divides."""
    kind = type(cfg).__name__
    if kind == "GPTConfig":
        hs = cfg.hidden_size
        return {
            "qkv": (hs, 3 * hs, "out"),
            "proj": (hs, hs, "in"),
            "fc_in": (hs, cfg.mlp_ratio * hs, "out"),
            "fc_out": (cfg.mlp_ratio * hs, hs, "in"),
        }
    if kind == "LlamaConfig":
        hs, d = cfg.hidden_size, cfg.head_dim
        return {
            "wq": (hs, cfg.num_heads * d, "out"),
            "wk": (hs, cfg.num_kv_heads * d, "out"),
            "wv": (hs, cfg.num_kv_heads * d, "out"),
            "wo": (cfg.num_heads * d, hs, "in"),
            "w_gate": (hs, cfg.intermediate_size, "out"),
            "w_up": (hs, cfg.intermediate_size, "out"),
            "w_down": (cfg.intermediate_size, hs, "in"),
        }
    raise ValueError(f"no LoRA target table for config type {kind}")
