"""BERT-family bidirectional encoder, TPU-first.

Reference anchor: the transformer-kernel test models
(`tests/unit/modeling.py`, ~2400 LoC BERT impl) and
``DeepSpeedTransformerLayer`` (ops/transformer/transformer.py:296) — the
reference's "fastest BERT" training benchmark model (BASELINE.md row 1).
Same logical-axis partitioning as models/gpt2.py; attention is the shared
oracle/flash pair with ``causal=False``.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention.reference import mha_reference


@dataclasses.dataclass(unsafe_hash=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "auto"
    pre_layer_norm: bool = True        # reference kernel supports both
    activation: str = "gelu"           # "gelu" (tanh approx) | "gelu_exact"
    mlm_bias: bool = False             # HF cls.predictions.bias

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _dense(cfg, features, axes, name):
    from deepspeed_tpu.ops.quant.qdense import QDense
    return QDense(features, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                  kernel_init=nn.with_partitioning(
                      nn.initializers.normal(0.02), axes),
                  name=name)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic=True):
        cfg = self.cfg
        b, l, _ = x.shape
        h, d = cfg.num_heads, cfg.head_dim
        qkv = _dense(cfg, 3 * cfg.hidden_size, ("embed", "kv"), "qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, h, d)
        k = k.reshape(b, l, h, d)
        v = v.reshape(b, l, h, d)
        bias = None
        if attention_mask is not None:
            # [b, l] 1/0 mask -> additive [b, 1, 1, l]
            bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                             jnp.finfo(jnp.float32).min)
        impl = cfg.attn_impl
        if impl == "auto":
            impl = "flash" if (jax.default_backend() == "tpu" and
                               l % 128 == 0) else "reference"
        if bias is not None:
            impl = "reference"  # flash kernel has no bias support yet
        if impl == "flash":
            from deepspeed_tpu.ops.attention import flash_attention
            out = flash_attention(q, k, v, causal=False)
        else:
            out = mha_reference(q, k, v, causal=False, bias=bias)
        out = out.reshape(b, l, cfg.hidden_size)
        out = _dense(cfg, cfg.hidden_size, ("heads", "embed"), "proj")(out)
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic=True):
        cfg = self.cfg
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                           name="ln_attn")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                           name="ln_mlp")
        attn = BertSelfAttention(cfg, name="attn")
        if cfg.pre_layer_norm:
            x = x + attn(ln1(x), attention_mask, deterministic)
            h = ln2(x)
        else:
            x = ln1(x + attn(x, attention_mask, deterministic))
            h = x
        h = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "fc_in")(h)
        h = nn.gelu(h, approximate=cfg.activation != "gelu_exact")
        h = _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "fc_out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        if cfg.pre_layer_norm:
            return x + h
        return ln2(x + h)


class Bert(nn.Module):
    """Returns MLM logits [b, l, vocab] (the pretraining objective the
    reference's BERT benchmarks train)."""
    cfg: BertConfig

    qtensor_params = True   # QDense consumes QTensor kernels (int8 serving)

    @nn.compact
    def __call__(self, input_ids, deterministic=True, attention_mask=None,
                 token_type_ids=None):
        cfg = self.cfg
        b, l = input_ids.shape
        wte = self.param("word_embeddings", nn.with_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wpe = self.param("position_embeddings", nn.with_partitioning(
            nn.initializers.normal(0.02), ("seq", "embed")),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
        wte_v = wte.value if hasattr(wte, "value") else wte
        wpe_v = wpe.value if hasattr(wpe, "value") else wpe
        x = (wte_v.astype(cfg.dtype)[input_ids] +
             wpe_v.astype(cfg.dtype)[jnp.arange(l)][None])
        if cfg.type_vocab_size > 0:   # DistilBERT has no segment table
            wtt = self.param("token_type_embeddings", nn.with_partitioning(
                nn.initializers.normal(0.02), (None, "embed")),
                (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
            wtt_v = wtt.value if hasattr(wtt, "value") else wtt
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + wtt_v.astype(cfg.dtype)[token_type_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_embed")(x)

        layer = BertLayer
        if cfg.remat:
            layer = nn.remat(BertLayer, prevent_cse=False)
        for i in range(cfg.num_layers):
            x = layer(cfg, name=f"layer_{i}")(x, attention_mask,
                                              deterministic)

        # MLM head: transform + tied decoder (HF BertLMPredictionHead shape)
        h = _dense(cfg, cfg.hidden_size, ("embed", "embed"), "mlm_transform")(x)
        h = nn.gelu(h, approximate=cfg.activation != "gelu_exact")
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlm_ln")(h)
        logits = jnp.einsum("ble,ve->blv", h, wte_v.astype(cfg.dtype))
        if cfg.mlm_bias:
            b_dec = self.param("mlm_decoder_bias", nn.with_partitioning(
                nn.initializers.zeros_init(), ("vocab",)),
                (cfg.vocab_size,), cfg.param_dtype)
            b_dec = b_dec.value if hasattr(b_dec, "value") else b_dec
            logits = logits + b_dec.astype(cfg.dtype)
        return logits


def bert_mlm_loss_fn(logits, batch):
    """Masked-LM cross entropy; labels -100 = unmasked (ignored)."""
    labels = batch["labels"]
    logits = logits.astype(jnp.float32)
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = (logz - ll) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def bert_tiny(**overrides):
    kwargs = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                  intermediate_size=128, max_seq_len=128)
    kwargs.update(overrides)
    return BertConfig(**kwargs)


def bert_large(**overrides):
    return BertConfig(vocab_size=30522, hidden_size=1024, num_layers=24,
                      num_heads=16, intermediate_size=4096, max_seq_len=512,
                      **overrides)
