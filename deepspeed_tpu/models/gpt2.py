"""GPT-2-family causal transformer, TPU-first.

This is the flagship training model (BASELINE.json config #1: "HF GPT-2-small,
ZeRO stage-1"). Design notes:

* flax.linen with **logical axis names** on every param
  (``nn.with_partitioning``) — `vocab/embed/heads/kv/mlp` — so tensor
  parallelism is a sharding-rule choice (parallel/sharding.py), not a code
  change. The reference reaches TP via Megatron mpu objects
  (`deepspeed/__init__.py:59`); here it's `pjit` + rules.
* attention may route through the Pallas flash kernel (ops/attention) or the
  jnp reference oracle (CPU tests), selected by `attn_impl`.
* remat ("activation checkpointing", reference
  `runtime/activation_checkpointing/checkpointing.py`) is `nn.remat` on the
  block, policy from config.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.ops.attention.reference import causal_mask, mha_reference


@dataclasses.dataclass(unsafe_hash=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32          # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = False
    scan_layers: bool = False          # lax.scan over layers: stacked
    # params with a leading [num_layers] dim. One compiled block instead
    # of num_layers inlined copies (fast compiles at depth), and under
    # ZeRO-3 param offload XLA streams each layer's slice from host
    # memory per scan step. Training-path only: the KV-cache decode path
    # keeps per-layer modules, and MoE interleaving is unsupported.
    attn_impl: str = "auto"            # "auto" | "reference" | "flash"
    use_bias: bool = True
    tie_embeddings: bool = True
    layer_norm_eps: float = 1e-5       # HF GPT-2/OPT/BLOOM value
    activation: str = "gelu"           # "gelu" (GPT-2/BLOOM) | "relu" (OPT)
    pos_embed: str = "learned"         # "learned" | "none" (rotary/ALiBi)
    pos_offset: int = 0                # OPT stores positions at index+2
    embed_layernorm: bool = False      # BLOOM word_embeddings_layernorm
    use_alibi: bool = False            # BLOOM attention bias
    rotary_dim: int = 0                # >0: rotary on first dims (GPT-J/NeoX)
    rotary_interleaved: bool = False   # GPT-J rotate-every-two convention
    rope_base: float = 10000.0
    parallel_residual: bool = False    # x + attn(ln1 x) + mlp(...) (J/NeoX)
    single_ln: bool = False            # GPT-J: mlp reads ln_1's output
    attn_bias: Optional[bool] = None   # GPT-J: no attn biases; default use_bias
    qkv_bias: Optional[bool] = None    # GPT-Neo: qkv unbiased, proj biased
    # per-layer local-attention windows (GPT-Neo "global"/"local"
    # alternation): entry i is layer i's window size, 0 = full causal.
    # Empty = all global.
    attn_windows: tuple = ()
    lm_head_bias: bool = False         # GPT-J lm_head carries a bias
    # MoE (reference deepspeed/moe): every `moe_every`-th block swaps its MLP
    # for a sharded MoE layer
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_use_residual: bool = False
    moe_use_rts: bool = False          # Random Token Selection (top-1 drops)
    moe_loss_coef: float = 0.01

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def _dense(features, cfg, kernel_axes, name=None, use_bias=None):
    from deepspeed_tpu.ops.quant.qdense import QDense
    return QDense(
        features,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        use_bias=cfg.use_bias if use_bias is None else use_bias,
        kernel_init=nn.with_partitioning(
            nn.initializers.normal(stddev=0.02), kernel_axes),
        name=name)


def alibi_slopes(num_heads):
    """ALiBi per-head slopes (BLOOM attention; Press et al. closed form)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return jnp.asarray(pow2_slopes(num_heads), jnp.float32)
    closest = 2 ** math.floor(math.log2(num_heads))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return jnp.asarray(pow2_slopes(closest) + extra, jnp.float32)


class SelfAttention(nn.Module):
    cfg: GPTConfig
    window: int = 0   # >0: local sliding-window causal attention

    @nn.compact
    def __call__(self, x, deterministic=True, cache=None, positions=None):
        cfg = self.cfg
        b, l, _ = x.shape
        attn_bias = cfg.use_bias if cfg.attn_bias is None else cfg.attn_bias
        qkv_bias = attn_bias if cfg.qkv_bias is None else cfg.qkv_bias
        # multi-tenant serving: per-slot LoRA deltas ride the paged
        # cache as a stacked side input (models/lora.py); absent for
        # base-only traffic, so that path's trace is unchanged
        ad = cache.get("adapters") if cache is not None else None
        if ad is not None:
            from deepspeed_tpu.models.lora import adapter_rows, lora_delta
            ad_rows = adapter_rows(ad, cache)
        qkv = _dense(3 * cfg.hidden_size, cfg, ("embed", "kv"), name="qkv",
                     use_bias=qkv_bias)(x)
        if ad is not None and "qkv" in ad:
            qkv = qkv + lora_delta(x, ad["qkv"], ad_rows, ad["scale"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, l, cfg.num_heads, cfg.head_dim)
        v = v.reshape(b, l, cfg.num_heads, cfg.head_dim)
        if cfg.rotary_dim:
            from deepspeed_tpu.ops.attention.reference import (
                apply_partial_rotary)
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(l)[None], (b, l))
            q = apply_partial_rotary(q, positions, cfg.rotary_dim,
                                     base=cfg.rope_base,
                                     interleaved=cfg.rotary_interleaved)
            k = apply_partial_rotary(k, positions, cfg.rotary_dim,
                                     base=cfg.rope_base,
                                     interleaved=cfg.rotary_interleaved)

        new_cache = None
        if cache is not None and "k_pages" in cache:
            # paged serving path (serving/ subsystem): K/V live in a
            # shared fixed-page pool indexed through a per-slot page
            # table — sequences of any length share one preallocated
            # cache, and the jit signature is fixed by (slots, chunk,
            # pool, table) shapes regardless of request churn.
            assert self.window == 0, \
                "paged serving does not support local attn_windows yet"
            from deepspeed_tpu.ops.attention import (decode_attention,
                                                     paged_decode_attention)
            from deepspeed_tpu.ops.quant.kv import (paged_gather,
                                                    paged_write)
            k_pages, v_pages = cache["k_pages"], cache["v_pages"]
            num_pages, ps = k_pages.shape[0], k_pages.shape[1]
            pt = cache["page_table"]                     # [slots, maxp]
            max_len = pt.shape[1] * ps
            k_pos = jnp.arange(max_len)
            alibi = None
            if cfg.use_alibi:
                alibi = (alibi_slopes(cfg.num_heads)[None, :, None, None]
                         * k_pos[None, None, None, :])
            if "slot" in cache:
                # chunked prefill into ONE slot: b == 1, l == chunk;
                # rows past n_valid are padding — their K/V writes drop
                # (out-of-bounds page id) and their outputs are unused.
                # The chunk starts at lengths[slot], which a prefix-
                # cache hit seeds to the cached boundary (not 0, not
                # page-aligned): writes only touch positions >= it, so
                # shared read-only pages below the boundary stay
                # immutable, and the write-before-gather order makes
                # the copy-on-write tail page's stale region harmless
                # (every stale position is either overwritten first or
                # masked out by k_pos <= position)
                slot = cache["slot"]
                pos = positions[0]                       # [l]
                valid = jnp.arange(l) < cache["n_valid"]
                page_ids = jnp.where(valid, pt[slot, pos // ps], num_pages)
                # write through the (possibly int8/fp8-quantized) pool:
                # quantized pools carry parallel per-row scale pools
                # that the same masked page ids update atomically
                # (ops/quant/kv.py); float pools take the byte-identical
                # legacy path
                pools_out = paged_write(cache, page_ids, pos % ps,
                                        k[0], v[0])
                seq_ax = cache.get("seq_axis")
                if seq_ax is not None:
                    # sequence-parallel prefill (static trace-time
                    # marker — the engine's seq-parallel closure builds
                    # the cache with it): the paged_write above already
                    # landed the chunk's KV — with ids sequence-sharded,
                    # GSPMD all-gathers k/v over the axis for the pool
                    # scatter, the collective the comm ledger prices —
                    # and attention runs distributed over the axis
                    # against the pool gather.  Pages in the pool are
                    # identical to the chunked path's, so decode/COW/
                    # donation/handoff downstream never notice.
                    assert alibi is None, \
                        "sequence-parallel prefill does not support alibi"
                    from deepspeed_tpu import comm as dist
                    from deepspeed_tpu.sequence.prefill import (
                        paged_prefill_attention)
                    k_pref, v_pref = paged_gather(pools_out,
                                                  pt[slot][None], q.dtype)
                    out = paged_prefill_attention(
                        q, k, v, k_pref, v_pref, positions[0, 0],
                        dist.get_mesh(), axis=seq_ax,
                        impl=cache["seq_impl"])
                else:
                    k_slot, v_slot = paged_gather(pools_out, pt[slot][None],
                                                  q.dtype)
                    mask = k_pos[None, None, :] <= positions[:, :, None]
                    bias = jnp.where(mask, 0.0,
                                     jnp.finfo(jnp.float32).min)[:, None]
                    if alibi is not None:
                        bias = bias + alibi
                    out = decode_attention(q, k_slot, v_slot, bias=bias)
            elif "widths" in cache:
                # teacher-forced multi-token verify (speculative decode):
                # b == slots, l == K+1 candidate tokens per slot. Column
                # j of slot s writes position lengths[s] + j when
                # j < widths[s] (widths is already 0 for inactive slots)
                # and attends causally through the page table — one
                # batched forward scores every draft instead of one scan
                # step per token. Columns the verifier later rejects
                # leave stale K/V past the rolled-back length; that tail
                # is either overwritten before any later gather reads it
                # or masked out by the k_pos <= position bias.
                widths = cache["widths"]
                pos = positions                          # [slots, l]
                write = jnp.arange(l)[None, :] < widths[:, None]
                page_ids = jnp.where(
                    write, pt[jnp.arange(b)[:, None], pos // ps], num_pages)
                pools_out = paged_write(cache, page_ids, pos % ps, k, v)
                k_slot, v_slot = paged_gather(pools_out, pt, q.dtype)
                mask = k_pos[None, None, :] <= pos[:, :, None]
                bias = jnp.where(mask, 0.0,
                                 jnp.finfo(jnp.float32).min)[:, None]
                if alibi is not None:
                    bias = bias + alibi
                out = decode_attention(q, k_slot, v_slot, bias=bias)
            else:
                # continuous-batch decode: b == slots, l == 1; inactive
                # slots write nowhere and produce ignored outputs.
                # paged_decode_attention owns the kernel-vs-reference
                # dispatch (engine's paged_kernel mode rides the trace
                # scope): on a multi-device mesh the Pallas kernel
                # runs per-shard under shard_map — kv heads over
                # `model`, slots over `data`, the page table global —
                # so this call site never changes with the topology
                active = cache["active"]
                pos = positions[:, 0]                    # [slots]
                page_ids = jnp.where(active,
                                     pt[jnp.arange(b), pos // ps], num_pages)
                pools_out = paged_write(cache, page_ids, pos % ps,
                                        k[:, 0], v[:, 0])
                out = paged_decode_attention(
                    q, pools_out["k_pages"], pools_out["v_pages"], pt,
                    pos, bias=alibi,
                    k_scale=pools_out.get("k_scale"),
                    v_scale=pools_out.get("v_scale"))
            # multi-chip serving: pin the pools' kv-head sharding on the
            # updated arrays so GSPMD keeps the scatter/gather split over
            # the `model` axis (no-op on a single-device mesh); the
            # quantized scale pools share the payload's [pages, ps,
            # kv_heads, 1] axis family and pin identically
            from deepspeed_tpu.serving.sharding import constrain_kv_pages
            new_cache = {name: constrain_kv_pages(arr)
                         for name, arr in pools_out.items()}
        elif cache is not None:
            # decode: append k/v at cache["index"], attend over the valid
            # prefix with a positional mask (same scheme as models/llama.py)
            k_cache = lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype),
                (0, cache["index"], 0, 0))
            v_cache = lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype),
                (0, cache["index"], 0, 0))
            new_cache = {"k": k_cache, "v": v_cache,
                         "index": cache["index"] + l}
            max_len = k_cache.shape[1]
            k_pos = jnp.arange(max_len)
            mask = k_pos[None, None, :] <= positions[:, :, None]  # [b,l,max]
            if self.window > 0:
                mask &= k_pos[None, None, :] > \
                    positions[:, :, None] - self.window
            bias = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)[:, None]
            if cfg.use_alibi:
                # softmax is shift-invariant per query row, so
                # slopes * key_pos == slopes * (key_pos - query_pos)
                bias = bias + (alibi_slopes(cfg.num_heads)[None, :, None, None]
                               * k_pos[None, None, None, :])
            from deepspeed_tpu.ops.attention import decode_attention
            out = decode_attention(q, k_cache, v_cache, bias=bias)
        elif self.window > 0:
            # local sliding-window causal attention (GPT-Neo "local"):
            # query attends to keys in (q_pos - window, q_pos]
            q_pos = jnp.arange(l)[:, None]
            k_pos = jnp.arange(l)[None, :]
            mask = (k_pos <= q_pos) & (k_pos > q_pos - self.window)
            bias = jnp.where(mask, 0.0,
                             jnp.finfo(jnp.float32).min)[None, None]
            out = mha_reference(q, k, v, causal=False, bias=bias)
        elif cfg.use_alibi:
            k_pos = jnp.arange(l)
            bias = (alibi_slopes(cfg.num_heads)[None, :, None, None] *
                    k_pos[None, None, None, :])
            out = mha_reference(q, k, v, causal=True, bias=bias)
        else:
            impl = cfg.attn_impl
            if impl == "auto":
                # Pallas kernel needs block-aligned seq lens; oracle otherwise
                impl = "flash" if (jax.default_backend() == "tpu" and
                                   l % 128 == 0) else "reference"
            if impl == "flash":
                from deepspeed_tpu.ops.attention import flash_attention
                out = flash_attention(q, k, v, causal=True)
            elif impl in ("ring", "ulysses"):
                # sequence/context parallelism over the `sequence` mesh axis
                from deepspeed_tpu import comm as dist
                from deepspeed_tpu.sequence import DistributedAttention
                mesh = dist.get_mesh()
                assert mesh is not None and \
                    mesh.shape.get("sequence", 1) > 1, \
                    f"attn_impl={impl} needs a mesh with a sequence axis > 1"
                out = DistributedAttention(mesh, impl=impl)(q, k, v)
            else:
                out = mha_reference(q, k, v, causal=True)
        out = out.reshape(b, l, cfg.hidden_size)
        proj_in = out
        out = _dense(cfg.hidden_size, cfg, ("heads", "embed"), name="proj",
                     use_bias=attn_bias)(proj_in)
        if ad is not None and "proj" in ad:
            out = out + lora_delta(proj_in, ad["proj"], ad_rows,
                                   ad["scale"])
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out, new_cache


class MLP(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, deterministic=True, adapters=None, ad_rows=None):
        cfg = self.cfg
        h = _dense(cfg.mlp_ratio * cfg.hidden_size, cfg, ("embed", "mlp"),
                   name="fc_in")(x)
        if adapters is not None and "fc_in" in adapters:
            from deepspeed_tpu.models.lora import lora_delta
            h = h + lora_delta(x, adapters["fc_in"], ad_rows,
                               adapters["scale"])
        h = nn.relu(h) if cfg.activation == "relu" else \
            nn.gelu(h, approximate=cfg.activation != "gelu_exact")
        mid = h
        h = _dense(cfg.hidden_size, cfg, ("mlp", "embed"), name="fc_out")(mid)
        if adapters is not None and "fc_out" in adapters:
            from deepspeed_tpu.models.lora import lora_delta
            h = h + lora_delta(mid, adapters["fc_out"], ad_rows,
                               adapters["scale"])
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    cfg: GPTConfig
    use_moe: bool = False
    window: int = 0

    @nn.compact
    def __call__(self, x, deterministic=True, cache=None, positions=None,
                 pld_keep=None):
        cfg = self.cfg
        x_in = x
        ad = cache.get("adapters") if cache is not None else None
        ad_rows = None
        if ad is not None:
            from deepspeed_tpu.models.lora import adapter_rows
            ad_rows = adapter_rows(ad, cache)
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                           name="ln_1")(x)
        attn_out, new_cache = SelfAttention(cfg, self.window, name="attn")(
            ln1, deterministic, cache, positions)
        if cfg.parallel_residual:
            # GPT-J / GPT-NeoX: attn and mlp branch from the same input;
            # GPT-J (single_ln) feeds the mlp ln_1's output directly
            h = ln1 if cfg.single_ln else nn.LayerNorm(
                epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_2")(x)
            assert not self.use_moe, "parallel residual + MoE unsupported"
            mlp_out = MLP(cfg, name="mlp")(h, deterministic, ad, ad_rows)
            out = x + attn_out + mlp_out
        else:
            x = x + attn_out
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="ln_2")(x)
            if self.use_moe:
                from deepspeed_tpu.moe import MoE
                h, _, _ = MoE(hidden_size=cfg.hidden_size,
                              num_experts=cfg.moe_num_experts,
                              ffn_hidden_size=cfg.mlp_ratio * cfg.hidden_size,
                              k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor,
                              min_capacity=cfg.moe_min_capacity,
                              use_residual=cfg.moe_use_residual,
                              use_rts=cfg.moe_use_rts,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              name="moe")(h, deterministic)
            else:
                h = MLP(cfg, name="mlp")(h, deterministic, ad, ad_rows)
            out = x + h
        if pld_keep is not None:
            # progressive layer drop (reference
            # runtime/progressive_layer_drop.py + the PLD paper's
            # stochastic depth): with prob 1 - pld_keep the whole block
            # is skipped this step — the residual stream passes through.
            # Kept branches scale by 1/keep (inverted-dropout
            # convention) so the eval-time full-depth forward matches
            # the training-time expectation without a rescale pass.
            keep = jax.random.bernoulli(self.make_rng("pld"), pld_keep)
            scaled = x_in + (out - x_in) / pld_keep.astype(out.dtype)
            out = jnp.where(keep, scaled, x_in)
        return out, new_cache


def _make_embed_tables(mdl, cfg):
    """Create wte/wpe on `mdl` (shared by GPT2 and GPT2Embed so the init
    scales and logical axis names live in exactly one place)."""
    wte = mdl.param(
        "wte",
        nn.with_partitioning(nn.initializers.normal(0.02), ("vocab", "embed")),
        (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
    wte_v = wte.value if hasattr(wte, "value") else wte
    if cfg.pos_embed == "none":
        return wte_v, None
    wpe = mdl.param(
        "wpe",
        nn.with_partitioning(nn.initializers.normal(0.01), ("seq", "embed")),
        (cfg.max_seq_len + cfg.pos_offset, cfg.hidden_size), cfg.param_dtype)
    wpe_v = wpe.value if hasattr(wpe, "value") else wpe
    return wte_v, wpe_v


def _embed_tokens(wte_v, wpe_v, input_ids, cfg, positions=None):
    b, l = input_ids.shape
    x = wte_v.astype(cfg.dtype)[input_ids]
    if wpe_v is not None:
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(l)[None], (b, l))
        x = x + wpe_v.astype(cfg.dtype)[positions + cfg.pos_offset]
    return x


def _head_logits(x, cfg, *, wte_v=None, dense_ctor=None):
    """ln_f + LM projection; tied path multiplies by wte, untied builds a
    lm_head Dense (caller supplies the constructors so params land on the
    calling module)."""
    x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                     name="ln_f")(x)
    if cfg.tie_embeddings:
        assert wte_v is not None, "tied head needs the embedding table"
        return jnp.einsum("ble,ve->blv", x, wte_v.astype(cfg.dtype))
    return dense_ctor(cfg.vocab_size, cfg, ("embed", "vocab"),
                      name="lm_head", use_bias=cfg.lm_head_bias)(x)


class GPT2(nn.Module):
    """Returns logits [batch, len, vocab]; with ``cache`` returns
    (logits, new_cache) — same decode contract as models/llama.py."""
    cfg: GPTConfig

    # QDense layers consume QTensor kernel leaves directly (int8 serving
    # without whole-tree dequantization; inference/engine._materialize)
    qtensor_params = True

    @nn.compact
    def __call__(self, input_ids, deterministic=True, positions=None,
                 cache=None, pld_theta=None, rltd_keep=None):
        cfg = self.cfg
        b, l = input_ids.shape
        if rltd_keep is not None and (cache is not None or
                                      rltd_keep >= l):
            rltd_keep = None     # decode / schedule-complete: full layers
        if rltd_keep is not None:
            assert not any(cfg.attn_windows) and not cfg.use_alibi, \
                "random_ltd middle layers attend over the gathered " \
                "SUBsequence, where index distance != token distance — " \
                "local attn_windows / ALiBi biases would silently " \
                "change meaning; disable one of the two"
        paged = cache is not None and "page_table" in cache
        if positions is None:
            if paged:
                lens = cache["lengths"]
                if "slot" in cache:      # chunked prefill (b == 1)
                    positions = (lens[cache["slot"]] +
                                 jnp.arange(l))[None, :]
                elif "widths" in cache:  # teacher-forced verify (l == K+1)
                    positions = lens[:, None] + jnp.arange(l)[None, :]
                else:                    # continuous-batch decode (l == 1)
                    positions = lens[:, None]
                positions = jnp.broadcast_to(positions, (b, l))
            else:
                start = cache["layers"][0]["index"] if cache is not None \
                    else 0
                positions = jnp.broadcast_to(start + jnp.arange(l)[None],
                                             (b, l))

        wte_v, wpe_v = _make_embed_tables(self, cfg)
        x = _embed_tokens(wte_v, wpe_v, input_ids, cfg, positions)
        if cfg.embed_layernorm:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="ln_embed")(x)

        # progressive layer drop: keep prob shrinks with depth,
        # keep_l = 1 - (l/L) * (1 - theta) (PLD paper's progressive
        # schedule; theta from runtime/progressive_layer_drop.py via the
        # engine). Needs an apply-time "pld" rng.
        pld_keeps = None
        if pld_theta is not None and cache is None:
            fracs = (jnp.arange(cfg.num_layers) + 1.0) / cfg.num_layers
            pld_keeps = (1.0 - fracs * (1.0 - pld_theta)).astype(
                jnp.float32)

        block = Block
        if cfg.remat and cache is None:
            block = nn.remat(Block, prevent_cse=False)
        new_layer_caches = []
        if cfg.scan_layers and cache is None:
            assert cfg.moe_num_experts <= 1, \
                "scan_layers cannot interleave MoE blocks (heterogeneous)"
            assert not any(cfg.attn_windows), \
                "scan_layers needs homogeneous layers (no local windows)"
            assert rltd_keep is None, \
                "random_ltd keeps the first/last layers full-sequence " \
                "(heterogeneous shapes); use scan_layers=False"
            # one scanned block: params stack to [num_layers, ...] leaves
            # ('layers' logical axis). With the stacked leaves in host
            # memory (ZeRO-3 param offload) XLA's scan streams one layer
            # slice to HBM per step — the partitioned_param_coordinator's
            # prefetch loop (reference :218) as a compiler schedule.
            sc = dict(variable_axes={"params": 0},
                      split_rngs={"params": True, "dropout": True,
                                  "pld": True},
                      length=cfg.num_layers,
                      metadata_params={nn.PARTITION_NAME: "layers"})
            if pld_keeps is None:
                scanned = nn.scan(block, in_axes=(
                    nn.broadcast, nn.broadcast, nn.broadcast), **sc)
                x, _ = scanned(cfg, False, name="h_scan")(
                    x, deterministic, None, positions)
            else:   # per-layer keep prob rides the scan axis
                scanned = nn.scan(block, in_axes=(
                    nn.broadcast, nn.broadcast, nn.broadcast, 0), **sc)
                x, _ = scanned(cfg, False, name="h_scan")(
                    x, deterministic, None, positions, pld_keeps)
        else:
            if cfg.scan_layers:
                raise ValueError(
                    "scan_layers is a training-path option: the KV-cache "
                    "decode path needs per-layer modules. Serve with "
                    "scan_layers=False (unstack the h_scan leaves along "
                    "axis 0 into h_{i} subtrees).")
            for i in range(cfg.num_layers):
                use_moe = (cfg.moe_num_experts > 1 and
                           i % cfg.moe_every == cfg.moe_every - 1)
                win = cfg.attn_windows[i] if i < len(cfg.attn_windows) else 0
                layer_cache = cache["layers"][i] if cache is not None else None
                if paged:
                    layer_cache = dict(layer_cache,
                                       page_table=cache["page_table"])
                    for key in ("slot", "n_valid", "active", "widths",
                                "seq_axis", "seq_impl"):
                        if key in cache:
                            layer_cache[key] = cache[key]
                    if "adapters" in cache:
                        from deepspeed_tpu.models.lora import layer_adapters
                        layer_cache["adapters"] = layer_adapters(cache, i)
                pk = None if pld_keeps is None else pld_keeps[i]
                # random layerwise token dropping (reference
                # data_routing/basic_layer.py:14 RandomLayerTokenDrop):
                # middle layers see a random ordered subset of rltd_keep
                # tokens; dropped tokens carry their residual value past
                # the layer. First/last layers stay full-sequence (the
                # reference's default layer selection).
                if rltd_keep is not None and 0 < i < cfg.num_layers - 1:
                    from deepspeed_tpu.runtime.data_pipeline.random_ltd \
                        import (random_ltd_gather, random_ltd_indices,
                                random_ltd_scatter)
                    idx = random_ltd_indices(self.make_rng("rltd"), l,
                                             rltd_keep, b)
                    sub = random_ltd_gather(x, idx)
                    sub_pos = jnp.take_along_axis(positions, idx, axis=1)
                    sub_out, _ = block(cfg, use_moe, win, name=f"h_{i}")(
                        sub, deterministic, None, sub_pos, pk)
                    x = random_ltd_scatter(sub_out, idx, x)
                    new_layer_caches.append(None)
                    continue
                x, new_c = block(cfg, use_moe, win, name=f"h_{i}")(
                    x, deterministic, layer_cache, positions, pk)
                new_layer_caches.append(new_c)

        if paged and "slot" in cache:
            # chunked prefill consumes ONLY the boundary row — skip the
            # full-vocab head for the chunk's other positions (~30% of a
            # prefill step at gpt2-small shapes)
            x = lax.dynamic_slice_in_dim(x, cache["n_valid"] - 1, 1, axis=1)
        logits = _head_logits(x, cfg, wte_v=wte_v, dense_ctor=_dense)
        if paged:
            if "slot" in cache:
                lengths = cache["lengths"].at[cache["slot"]].add(
                    cache["n_valid"])
            elif "widths" in cache:
                # verify: widths columns written per slot (already 0 for
                # inactive slots); the engine's verify primitive rewinds
                # this to the emitted-token count after acceptance
                lengths = cache["lengths"] + cache["widths"]
            else:
                lengths = cache["lengths"] + \
                    cache["active"].astype(jnp.int32)
            out_cache = dict(cache, lengths=lengths,
                             layers=new_layer_caches)
            return logits, out_cache
        if cache is not None:
            return logits, {"layers": new_layer_caches}
        return logits


def gpt2_loss_fn(logits, batch):
    """Mean next-token cross-entropy; expects batch['labels'] (already
    shifted) or computes shift from input_ids.

    HBM note: the label gather reads the RAW (bf16) logits and only the
    gathered [b, l] column upcasts — converting the whole tensor first
    would force XLA to materialize a full fp32 copy as the gather
    operand (1.6 GB at gpt2-small bench shapes). The logsumexp's upcast
    fuses into its reduction, so no fp32 tensor ever lands in HBM."""
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.pad(batch["input_ids"][:, 1:], ((0, 0), (0, 1)),
                         constant_values=-100)
    valid = labels >= 0
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1)[..., 0].astype(jnp.float32)
    nll = (logz - ll) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


class GPT2Embed(nn.Module):
    """Embedding front (outside the pipelined region in PP)."""
    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids):
        wte_v, wpe_v = _make_embed_tables(self, self.cfg)
        return _embed_tokens(wte_v, wpe_v, input_ids, self.cfg)


class GPT2Head(nn.Module):
    """Final norm + LM projection (outside the pipelined region in PP).
    With cfg.tie_embeddings the decoder reuses the embedding table, passed
    in as `embed_params` by PipelineModule (tied_head=True)."""
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, embed_params=None):
        cfg = self.cfg
        wte_v = None
        if cfg.tie_embeddings:
            assert embed_params is not None, \
                "tie_embeddings needs PipelineModule(tied_head=True)"
            wte_v = embed_params["wte"]
            wte_v = wte_v.value if hasattr(wte_v, "value") else wte_v
        return _head_logits(x, cfg, wte_v=wte_v, dense_ctor=_dense)


def gpt2_pipeline(cfg, num_stages, num_microbatches=None, layer_weights=None,
                  schedule="1f1b"):
    """GPT-2 as a pipeline-parallel model (reference PipelineModule usage,
    e.g. Megatron GPT on DeepSpeed PP). Honors cfg.tie_embeddings via the
    PipelineModule tied-head path (reference TiedLayerSpec);
    `layer_weights` gives non-uniform stage partitioning
    (reference partition_balanced)."""
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    return PipelineModule(block=Block(cfg), num_blocks=cfg.num_layers,
                          num_stages=num_stages,
                          embed=GPT2Embed(cfg), head=GPT2Head(cfg),
                          num_microbatches=num_microbatches,
                          tied_head=cfg.tie_embeddings,
                          layer_weights=layer_weights, schedule=schedule)


def init_kv_cache(cfg: GPTConfig, batch_size, max_len=None,
                  dtype=jnp.bfloat16):
    """Empty KV cache pytree (reference inference_context.h workspace);
    same contract as models/llama.py init_kv_cache."""
    max_len = max_len or cfg.max_seq_len
    layer = lambda: {
        "k": jnp.zeros((batch_size, max_len, cfg.num_heads, cfg.head_dim),
                       dtype),
        "v": jnp.zeros((batch_size, max_len, cfg.num_heads, cfg.head_dim),
                       dtype),
        "index": jnp.int32(0),
    }
    return {"layers": [layer() for _ in range(cfg.num_layers)]}


def init_paged_kv_cache(cfg: GPTConfig, num_pages, page_size,
                        dtype=jnp.bfloat16):
    """Per-layer paged KV pools (serving/ subsystem): ``num_pages`` fixed
    pages of ``page_size`` tokens shared by every live sequence through a
    page table. The table/lengths/active arrays are host-owned (the
    scheduler passes them per call); only the pools live here.
    ``dtype`` may be a quantized kv-dtype name ("int8"/"fp8"): the
    layer then carries int8/fp8 payload pools plus parallel per-row f32
    scale pools (ops/quant/kv.py storage contract)."""
    from deepspeed_tpu.ops.quant.kv import paged_pool_layer
    layer = lambda: paged_pool_layer(num_pages, page_size, cfg.num_heads,
                                     cfg.head_dim, dtype)
    return {"layers": [layer() for _ in range(cfg.num_layers)]}


# canonical "HF GPT-2 small" hyperparameters
def gpt2_small(**overrides):
    return GPTConfig(vocab_size=50257, hidden_size=768, num_layers=12,
                     num_heads=12, max_seq_len=1024, **overrides)


def gpt2_tiny(**overrides):
    """Test fixture scale (reference tests/unit/simple_model.py spirit)."""
    kwargs = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                  max_seq_len=128)
    kwargs.update(overrides)
    return GPTConfig(**kwargs)
