"""Model families (flax, logical-axis partitioned).

Reference counterparts: the HF architectures deepspeed's inference policy
registry covers (module_inject/replace_policy.py: BERT/GPT2/GPT-J/NeoX/
OPT/BLOOM/...) plus the training fixtures (tests/unit/simple_model.py,
tests/unit/modeling.py). Here each family is a native flax model whose
params carry logical axis names, so TP/FSDP/EP are sharding-rule choices.
"""

from deepspeed_tpu.models.gpt2 import (GPT2, GPTConfig, gpt2_loss_fn,  # noqa: F401
                                       gpt2_small, gpt2_tiny)
from deepspeed_tpu.models.llama import (Llama, LlamaConfig,  # noqa: F401
                                        init_kv_cache, llama2_7b,
                                        llama2_70b, llama_tiny)
from deepspeed_tpu.models.bert import (Bert, BertConfig,  # noqa: F401
                                       bert_large, bert_mlm_loss_fn,
                                       bert_tiny)

# generic causal-LM loss: gpt2's implementation is model-agnostic
causal_lm_loss_fn = gpt2_loss_fn
