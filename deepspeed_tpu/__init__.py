"""deepspeed_tpu: TPU-native large-scale training & inference framework.

Keeps the reference's user-facing factory surface
(``deepspeed/__init__.py`` — ``initialize`` :53, ``init_inference`` :215,
``add_config_arguments`` :192) on a JAX/XLA/Pallas/pjit core.
"""

from deepspeed_tpu.utils import jax_compat as _jax_compat

_jax_compat.install()   # jax.shard_map alias on jax<0.5 runtimes

from deepspeed_tpu.version import __version__  # noqa: F401,E402
from deepspeed_tpu import comm  # noqa: F401,E402
from deepspeed_tpu.utils.logging import log_dist, logger  # noqa: F401,E402


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None, mesh=None,
               dist_init_required=None, collate_fn=None, config=None,
               config_params=None, loss_fn=None, example_batch=None, seed=0):
    """Build a training engine (reference ``deepspeed.initialize``).

    Arguments mirror the reference where meaningful on TPU:
      model: a flax.linen Module (the "client model").
      loss_fn: optional ``loss_fn(params, batch, rng) -> scalar``; defaults to
        the causal-LM contract (module(input_ids)->logits, next-token CE).
      config: JSON path or dict (same schema as the reference config).
      mesh: optional prebuilt jax.sharding.Mesh; otherwise built from the
        config's "mesh" section over all visible devices.
      example_batch: optional batch for eager parameter initialization;
        otherwise params initialize lazily on the first forward().

    Returns (engine, optimizer, training_dataloader, lr_scheduler) like the
    reference; `optimizer` is the engine's optax transformation.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    assert config is not None, "deepspeed_tpu.initialize: config is required"

    engine = DeepSpeedEngine(model=model, config=config, loss_fn=loss_fn,
                             mesh=mesh, training_data=training_data,
                             lr_scheduler=lr_scheduler, collate_fn=collate_fn,
                             example_batch=example_batch, seed=seed,
                             client_optimizer=optimizer)
    return engine, engine.tx, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Build an inference engine (reference ``deepspeed.init_inference``).

    ``model`` may be a native flax module, a HF transformers model
    instance, or a path to an HF checkpoint directory — the latter two
    are ingested through the policy system
    (``module_inject/replace_module.py:274`` capability)."""
    from deepspeed_tpu.inference.engine import DTYPES, InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    params = kwargs.pop("params", None)
    mesh = kwargs.pop("mesh_obj", None)
    if isinstance(config, DeepSpeedInferenceConfig):
        # re-validate so nested dicts/aliases in kwargs are coerced
        cfg = DeepSpeedInferenceConfig(**{**config.model_dump(), **kwargs}) \
            if kwargs else config
    else:
        if isinstance(config, str):
            import json
            with open(config) as f:
                config = json.load(f)
        merged = dict(config or {})
        merged.update(kwargs)
        cfg = DeepSpeedInferenceConfig(**merged)

    is_hf_instance = hasattr(model, "state_dict") and hasattr(model, "config")
    is_hf_dir = False
    if isinstance(model, str):
        import os
        is_hf_dir = os.path.isdir(model) and (
            os.path.exists(os.path.join(model, "config.json")))
    if is_hf_instance or is_hf_dir:
        if cfg.dtype not in DTYPES:
            raise ValueError(
                f"unsupported inference dtype {cfg.dtype!r}; pick one of "
                f"{sorted(DTYPES)} or 'int8' (weight-only quantization)")
        from deepspeed_tpu.module_inject import from_hf
        model, params = from_hf(model, dtype=DTYPES[cfg.dtype])
    return InferenceEngine(model, cfg, params=params, mesh=mesh)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config to an argparse parser
    (reference ``deepspeed/__init__.py:192``)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity with reference)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration")
    return parser
