"""Monitor config (reference: ``deepspeed/monitor/config.py:63``)."""

from typing import Optional

from pydantic import model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


def get_monitor_config(param_dict):
    monitor_dict = {
        key: param_dict.get(key) or {}
        for key in ("tensorboard", "wandb", "csv_monitor")
    }
    return DeepSpeedMonitorConfig(**monitor_dict)


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = {}
    wandb: WandbConfig = {}
    csv_monitor: CSVConfig = {}

    @model_validator(mode="after")
    def _any_enabled(self):
        object.__setattr__(
            self, "enabled",
            self.tensorboard.enabled or self.wandb.enabled or self.csv_monitor.enabled)
        return self
