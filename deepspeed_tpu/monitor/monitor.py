"""Experiment monitors (reference: ``deepspeed/monitor/monitor.py`` —
``Monitor`` ABC :13, ``MonitorMaster`` :29 fanning out to TensorBoard,
WandB and CSV writers). Events are ``(tag, value, step)`` tuples."""

import csv
import os
from abc import ABC, abstractmethod
from collections import deque

from deepspeed_tpu.utils.logging import logger


class Monitor(ABC):
    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    @abstractmethod
    def write_events(self, event_list):
        pass


_step_clamp_warned = set()   # tags already warned about (once per proc)


def clamp_min_step(event_list, warn=True):
    """Enforce the monitor-stream invariant ``step >= 1`` centrally.

    Every sink indexes events by a positive step (TensorBoard's global
    step, the CSV step column, wandb's step) — a 0/negative step either
    errors or silently lands before the run's first point.  Rather than
    each emitter hand-stamping (the old ``record_mesh`` workaround),
    events pass through here: offending steps are clamped to 1 and, with
    ``warn``, logged once per tag so the emitter can be fixed.  Emitters
    with *documented* pre-step-1 events (serving construction-time
    gauges) clamp with ``warn=False``."""
    if all(e[2] >= 1 for e in event_list):
        return event_list
    out = []
    for tag, value, step in event_list:
        if step < 1:
            if warn and tag not in _step_clamp_warned:
                _step_clamp_warned.add(tag)
                logger.warning(
                    f"monitor event {tag!r} stamped with step {step} < 1;"
                    " clamped to 1 (sinks index by positive step)")
            step = 1
        out.append((tag, value, step))
    return out


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = config.enabled
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(config.output_path or "./runs",
                                       config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                logger.warning(f"TensorBoard unavailable ({e}); disabled")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for event in event_list:
            self.summary_writer.add_scalar(*event)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = config.enabled
        if self.enabled:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group,
                           entity=config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabled")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=int(step))


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.enabled = config.enabled
        self.output_path = config.output_path or "./csv_monitor"
        self.job_name = config.job_name
        self.filenames = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name),
                        exist_ok=True)

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            safe = name.replace("/", "_")
            fname = os.path.join(self.output_path, self.job_name, safe + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", safe])
                w.writerow([int(step), float(value)])


class RingBufferMonitor(Monitor):
    """Bounded in-memory event sink (same ``write_events`` contract as
    the file-backed monitors). The resilience supervisor and the serving
    health endpoint keep their recent event history here so a live
    process can be interrogated (``tail()``) without any sink
    configured — and tests can assert on emitted events directly."""

    def __init__(self, maxlen=1024):
        super().__init__(None)
        self.enabled = True
        self.events = deque(maxlen=maxlen)

    def write_events(self, event_list):
        self.events.extend(event_list)

    def tail(self, n=20):
        return list(self.events)[-n:]


class MonitorMaster(Monitor):
    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
        self.wandb_monitor = WandbMonitor(monitor_config.wandb)
        self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
        self.enabled = getattr(monitor_config, "enabled", False)

    def write_events(self, event_list):
        # central invariant enforcement: no sink ever sees step < 1
        event_list = clamp_min_step(event_list)
        if self.tb_monitor.enabled:
            self.tb_monitor.write_events(event_list)
        if self.wandb_monitor.enabled:
            self.wandb_monitor.write_events(event_list)
        if self.csv_monitor.enabled:
            self.csv_monitor.write_events(event_list)
