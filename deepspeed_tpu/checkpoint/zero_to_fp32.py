"""Offline consolidation of a sharded checkpoint into one fp32 .npz.

Reference: ``deepspeed/utils/zero_to_fp32.py:313,362`` — the script users
run on a ZeRO checkpoint directory to merge per-rank partitioned fp32
state into a single loadable state dict. Here chunks are globally indexed
so consolidation is a streaming merge, one leaf in memory at a time.

Usage::

    python -m deepspeed_tpu.checkpoint.zero_to_fp32 <ckpt_dir> <out.npz>

``<ckpt_dir>`` may be the run directory (the ``latest`` file is followed,
like the reference) or a specific ``<dir>/<tag>`` directory.
"""

import argparse
import os
import sys

from deepspeed_tpu.checkpoint.engine import _META, consolidate


def resolve_tag_dir(path):
    if os.path.exists(os.path.join(path, _META)) or \
            os.path.exists(os.path.join(path, "model_states.npz")):
        return path
    latest = os.path.join(path, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            return os.path.join(path, f.read().strip())
    raise FileNotFoundError(f"{path} is not a checkpoint directory")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Merge a sharded deepspeed_tpu checkpoint into a "
                    "single fp32 .npz of model weights.")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--prefix", default=".params",
                   help="pytree path prefix of the weights subtree")
    args = p.parse_args(argv)
    tag_dir = resolve_tag_dir(args.checkpoint_dir)
    out = consolidate(tag_dir, args.output_file, prefix=args.prefix)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
