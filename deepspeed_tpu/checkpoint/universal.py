"""Universal checkpoints: per-param fp32 fragments any partitioning can
load, plus the offline 3D (tp, pp) Megatron merge that produces them.

Reference: ``deepspeed/checkpoint/universal_checkpoint.py:12`` (per-param
fp32 "hp" fragments loadable into any partitioning),
``reshape_3d_utils.py:17`` / ``reshape_meg_2d.py`` (re-slicing Megatron
tp/pp/dp checkpoints), and the offline driver ``ds_to_universal``.

TPU shape of the idea: the fragment store is a directory of
``<param-name>.npy`` fp32 files (names = the engine's checkpoint leaf
names, ``param_leaf_names``) plus optional ``<name>.m.npy``/``.v.npy``
Adam moments and a ``meta.json``. ``DeepSpeedEngine.
load_universal_checkpoint`` maps fragments onto the live state tree —
whatever the mesh/ZeRO stage, each leaf is device_put to its own
sharding, so "any partitioning" needs no reshape logic at all here.

The Megatron merge undoes tensor parallelism by key pattern
(ColumnParallel: concat out-dim; RowParallel: concat in-dim; embeddings:
concat vocab; layernorms/biases-of-row: replicated) and pipeline
parallelism by renumbering each stage's layers at its global offset —
then MegatronGPT2Policy.convert maps the merged dict onto the native
GPT2 tree.
"""

import json
import os
import re

import numpy as np

import jax

# TP merge rules for Megatron-LM GPT state dicts, by key suffix.
# cat0 = ColumnParallel (output dim sharded), cat1 = RowParallel (input
# dim sharded), rep = replicated across tp ranks.
_TP_RULES = (
    (r"word_embeddings\.weight$", "cat0"),
    (r"position_embeddings\.weight$", "rep"),
    (r"query_key_value\.weight$", "cat0"),
    (r"query_key_value\.bias$", "cat0"),
    (r"attention\.dense\.weight$", "cat1"),
    (r"attention\.dense\.bias$", "rep"),
    (r"dense_h_to_4h\.weight$", "cat0"),
    (r"dense_h_to_4h\.bias$", "cat0"),
    (r"dense_4h_to_h\.weight$", "cat1"),
    (r"dense_4h_to_h\.bias$", "rep"),
    (r"layernorm\.(weight|bias)$", "rep"),
    (r"\.(weight|bias)$", "rep"),    # fallback: anything not sharded
)

_LAYER_RE = re.compile(r"(.*\blayers\.)(\d+)(\..*)")


def _tp_rule(key):
    for pat, rule in _TP_RULES:
        if re.search(pat, key):
            return rule
    return "rep"


def merge_megatron_tp(shards):
    """Merge one pipeline stage's tp shards (list of state dicts, tp-rank
    order) into a single-unit state dict."""
    out = {}
    for key in shards[0]:
        vals = [np.asarray(s[key]) for s in shards]
        if np.ndim(vals[0]) == 0:
            out[key] = vals[0]
            continue
        rule = _tp_rule(key)
        if rule == "cat0":
            out[key] = np.concatenate(vals, axis=0)
        elif rule == "cat1":
            out[key] = np.concatenate(vals, axis=1)
        else:
            out[key] = vals[0]
    return out


def merge_megatron_3d(stages):
    """``stages[pp_rank] = [sd_tp0, sd_tp1, ...]`` -> one merged state
    dict with globally renumbered layers (reference reshape_3d_utils
    semantics: undo tp within each stage, then concatenate stages'
    layer ranges)."""
    merged = {}
    offset = 0
    for pp_rank, tp_shards in enumerate(stages):
        sd = merge_megatron_tp(tp_shards)
        max_local = -1
        for key, val in sd.items():
            m = _LAYER_RE.match(key)
            if m:
                local = int(m.group(2))
                max_local = max(max_local, local)
                merged[f"{m.group(1)}{local + offset}{m.group(3)}"] = val
            else:
                # stage-resident singletons (embeddings on the first
                # stage, final layernorm on the last) merge by name;
                # identical duplicates (tied embeddings on both ends)
                # are fine to overwrite
                merged[key] = val
        offset += max_local + 1
    return merged


# ---------------------------------------------------------------- fragments
def save_universal(path, named_params, named_moments=None, meta=None):
    """Write per-param fp32 fragments: ``named_params`` maps checkpoint
    leaf name -> array; ``named_moments`` maps name -> (m, v)."""
    os.makedirs(path, exist_ok=True)
    names = []
    for name, arr in named_params.items():
        fn = _frag_file(path, name)
        np.asarray(arr, np.float32).tofile(fn + ".bin")
        names.append(name)
        mv = (named_moments or {}).get(name)
        if mv is not None:
            np.asarray(mv[0], np.float32).tofile(fn + ".m.bin")
            np.asarray(mv[1], np.float32).tofile(fn + ".v.bin")
    info = {"format": "ds_tpu_universal_v1",
            "leaves": {n: {"shape": list(np.shape(named_params[n])),
                           "has_moments":
                               (named_moments or {}).get(n) is not None}
                       for n in names}}
    info.update(meta or {})
    with open(os.path.join(path, "universal_meta.json"), "w") as f:
        json.dump(info, f, indent=2)


def _frag_file(path, name):
    # leaf names contain '/' and '.'; flatten to a safe filename
    return os.path.join(path, name.strip(".").replace("/", "__")
                        .replace(".", "__"))


def load_universal(path):
    """-> (meta, {name: fp32 array}, {name: (m, v) or None})."""
    with open(os.path.join(path, "universal_meta.json")) as f:
        meta = json.load(f)
    params, moments = {}, {}
    for name, info in meta["leaves"].items():
        fn = _frag_file(path, name)
        shape = tuple(info["shape"])
        # memmaps, not eager reads: the NVMe-offload resume path
        # consumes one leaf at a time (init_master takes a generator) —
        # params+m+v of a tier-scale model must never be resident at once
        params[name] = np.memmap(fn + ".bin", np.float32, "r",
                                 shape=shape)
        if info.get("has_moments"):
            moments[name] = (
                np.memmap(fn + ".m.bin", np.float32, "r", shape=shape),
                np.memmap(fn + ".v.bin", np.float32, "r", shape=shape))
        else:
            moments[name] = None
    return meta, params, moments


def megatron_to_universal(stages, hf_config, out_path):
    """Offline conversion (the reference's ``ds_to_universal`` for
    Megatron sources): merge the (pp, tp) shard grid, convert to the
    native GPT2 tree via the inference policy's layout knowledge, and
    write fragments under the engine's checkpoint leaf names."""
    from deepspeed_tpu.checkpoint.engine import param_leaf_names
    from deepspeed_tpu.module_inject.policy import MegatronGPT2Policy

    merged = merge_megatron_3d(stages)
    params = MegatronGPT2Policy.convert(hf_config, merged)
    names = param_leaf_names(params)
    leaves = jax.tree.leaves(params)
    save_universal(out_path, dict(zip(names, leaves)),
                   meta={"source": "megatron-lm",
                         "num_layers": int(hf_config.num_layers)})
    return MegatronGPT2Policy.build_module(hf_config)
