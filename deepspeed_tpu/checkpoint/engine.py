"""Checkpoint save/load of sharded train state.

Reference: ``runtime/checkpoint_engine/checkpoint_engine.py`` (torch.save) and
engine ``save_checkpoint``/``load_checkpoint`` (engine.py:2818/2513). Arrays
are addressed by pytree path, saved as a single .npz (gathered to host), and
restored back onto whatever mesh/sharding the *current* run uses — which
makes every checkpoint "universal" in the reference's sense
(``deepspeed/checkpoint/universal_checkpoint.py``): a run with a different
mesh layout or ZeRO stage can load it, because sharding is re-applied at
restore, not baked into the file.
"""

import json
import os

import jax
import numpy as np


def _flatten_named(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = [jax.tree_util.keystr(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def save_state(path, state, client_state=None):
    os.makedirs(path, exist_ok=True)
    names, leaves, _ = _flatten_named(state)
    arrays = {}
    for name, leaf in zip(names, leaves):
        arrays[name] = np.asarray(jax.device_get(leaf))
    np.savez(os.path.join(path, "model_states.npz"), **arrays)
    with open(os.path.join(path, "client_state.json"), "w") as f:
        json.dump(client_state or {}, f, indent=2, default=str)


def load_state(path, target_state, mesh=None):
    """Restore into the structure/shardings of `target_state`."""
    state = load_subtree(path, target_state, prefix="")
    client = {}
    cs = os.path.join(path, "client_state.json")
    if os.path.exists(cs):
        with open(cs) as fh:
            client = json.load(fh)
    return state, client


def load_subtree(path, target, prefix=""):
    """Restore a subtree of a saved state into `target` (same structure),
    re-applying each target leaf's sharding/dtype. `prefix` addresses the
    subtree inside the saved pytree (e.g. ".params" for the TrainState's
    parameter branch) — the engine-side half of the reference's
    universal-checkpoint param-fragment loading
    (deepspeed/checkpoint/universal_checkpoint.py:12)."""
    f = os.path.join(path, "model_states.npz")
    if not os.path.exists(f):
        raise FileNotFoundError(f"checkpoint file not found: {f}")
    data = np.load(f, allow_pickle=False)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    new = []
    for path_k, leaf in flat:
        key = prefix + jax.tree_util.keystr(path_k)
        if key not in data:
            raise KeyError(f"checkpoint missing entry {key}")
        arr = data[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for {key}: checkpoint "
                             f"{arr.shape} vs target {np.shape(leaf)}")
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            new.append(jax.device_put(arr.astype(leaf.dtype), sharding))
        else:
            new.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new)
