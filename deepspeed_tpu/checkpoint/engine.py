"""Sharded checkpoint save/load of distributed train state.

Reference: engine ``save_checkpoint``/``load_checkpoint``
(``runtime/engine.py:2818/2513``, per-rank ``*_optim_states.pt`` files),
the ``CheckpointEngine`` abstraction
(``runtime/checkpoint_engine/checkpoint_engine.py:9`` — Torch vs Nebula
async tiered persistence), and the offline reshape/universal tools
(``deepspeed/checkpoint/reshape_3d_utils.py:17``,
``universal_checkpoint.py:12``).

TPU-native shape of the idea
----------------------------
A checkpoint is a directory of **per-process chunk files**. Every process
writes exactly the array shards its local devices own (deduplicated by
``replica_id == 0``), so no host ever materializes the full state and save
bandwidth scales with the number of hosts — the property the reference
gets from per-rank ``*_optim_states.pt`` files. Chunks are addressed by
*global index*, not by rank or mesh: the key is ``<leaf>|<start:stop,...>``.
That makes every checkpoint **universal** in the reference's sense: a run
with a different mesh, process count, or ZeRO stage rebuilds each leaf by
assembling whatever chunk rectangles cover the slice its own devices need.
Nothing in the file layout encodes the writer's parallelism.

Layout::

    <dir>/<tag>/
      checkpoint_meta.json        # format, leaf -> {shape, dtype}, client state
      shards_p00000.npz           # chunk files, one per writing process
      shards_p00001.npz
      host_optim_states.npz       # (ZeRO-Offload) fp32 master + moments

Async save (the Nebula-engine capability) runs the device→host transfer
and file write on a background thread; ``AsyncCheckpointWriter.wait()``
joins it, and the engine exposes ``wait_checkpoint()``.
"""

import io
import json
import os
import threading
import time
import zipfile
import zlib

import jax
import numpy as np

from deepspeed_tpu import tracing
from deepspeed_tpu.resilience import faults

_META = "checkpoint_meta.json"
_FORMAT = 2


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed an integrity check (CRC mismatch, truncated
    shard file, missing chunk coverage). Callers roll back to an older
    intact tag rather than restoring partial/garbage state."""


def _flatten_named(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = [jax.tree_util.keystr(path) for path, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def _index_key(index, shape):
    """Canonical string for a global index: 'start:stop,start:stop,...'.
    Scalar arrays use the empty string."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def _parse_index(key):
    if not key:
        return ()
    return tuple(slice(int(a), int(b))
                 for a, b in (p.split(":") for p in key.split(",")))


def _full_index(shape):
    return tuple(slice(0, d) for d in shape)


def _write_npz_streaming(path, chunk_iter):
    """Write an .npz one entry at a time (np.savez holds everything in
    memory at once; a checkpoint writer must stay chunk-sized). Returns
    ``{entry_key: crc32}`` over the stored .npy member bytes — the same
    CRC zipfile records in the central directory (ZIP_STORED), so the
    meta-recorded value and the zip-internal value cross-check."""
    crcs = {}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED, allowZip64=True) as z:
        for key, arr in chunk_iter:
            arr = np.ascontiguousarray(arr)
            if arr.dtype.kind == "V" and arr.dtype.itemsize in (1, 2):
                # ml_dtypes extension dtypes (bfloat16, fp8) have no
                # portable npy descr: store the raw bits as a uint view;
                # the reader re-views from the meta's recorded leaf dtype
                arr = arr.view(np.uint16 if arr.dtype.itemsize == 2
                               else np.uint8)
            if arr.ndim == 0:
                # this numpy's NpzFile reads 0-d entries back as (1,);
                # store scalars as (1,) on purpose and reshape at read
                arr = arr.reshape(1)
            buf = io.BytesIO()
            np.lib.format.write_array(buf, arr, allow_pickle=False)
            z.writestr(key + ".npy", buf.getvalue())
            crcs[key] = z.getinfo(key + ".npy").CRC
    return crcs


def _leaf_chunks(leaf):
    """Yield (index_key, host_array) for the shards of `leaf` this process
    owns, deduplicated across replicas. Non-jax leaves yield one full
    chunk from process 0 only."""
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        seen = set()
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            key = _index_key(shard.index, leaf.shape)
            if key in seen:
                continue
            seen.add(key)
            yield key, np.asarray(shard.data)
    elif jax.process_index() == 0:
        arr = np.asarray(leaf)
        yield _index_key(_full_index(arr.shape), arr.shape), arr


def _coordination_client():
    """The distributed coordination-service client, or None outside a
    jax.distributed-initialized run. Lives in jax's private distributed
    module (there is no public accessor as of jax 0.9)."""
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None)
    except Exception:
        return None


def param_leaf_names(params, prefix=".params"):
    """Checkpoint leaf names for a params tree, in flat order — the same
    names _flatten_named assigns when the tree sits under the state's
    ``params`` field. Single source of the naming contract shared by the
    engine (offload master pairing) and consolidate()."""
    names, _, _ = _flatten_named(params)
    return [prefix + n for n in names]


def _durability_barrier(save_id, path, on_writer_thread):
    """Block until every process's shard file is durably written.

    In async mode this runs on the *writer thread*, so it must not be a
    device collective: the main thread keeps issuing train-step
    collectives, and two threads enqueueing collectives in host-dependent
    order can deadlock or mismatch across hosts. Preferred channel is the
    coordination service's barrier (the same channel Orbax uses) — a pure
    host-side RPC that never touches the devices. Without a coordination
    client, the sync path uses the device barrier (safe on the main
    thread) and the async path polls the checkpoint directory for every
    process's shard file — valid because multi-process checkpoints
    require a shared directory (the loader assembles all shard files)."""
    if jax.process_count() == 1:
        return
    client = _coordination_client()
    if client is not None:
        client.wait_at_barrier(f"ckpt_done:{save_id}", 600_000)
        return
    if not on_writer_thread:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_done:{save_id}")
        return
    # writer thread, no coordination client: EVERY process watches for all
    # processes' shard files to appear in the shared directory, so any
    # rank's wait_checkpoint() implies global durability (matching the
    # coordination-service barrier's semantics) — not just process 0's,
    # which additionally flips the `latest` pointer in on_done
    import time
    deadline = time.time() + 600.0
    want = jax.process_count()
    while True:
        done = sum(1 for fn in os.listdir(path)
                   if fn.startswith("shards_p") and fn.endswith(".npz")
                   and f".{save_id}." in fn)
        if done >= want:
            return
        if time.time() > deadline:
            raise TimeoutError(
                f"checkpoint barrier: only {done}/{want} shard files for "
                f"save {save_id} appeared in {path} after 600s")
        time.sleep(0.25)


def _agree_save_id():
    """One save_id shared by ALL processes: generated on process 0 and
    broadcast. A per-process uuid would stamp every host's shard file
    differently — the loader (which trusts the meta's id) would then drop
    every non-process-0 shard."""
    import uuid
    if jax.process_count() == 1:
        return uuid.uuid4().hex[:12]
    from jax.experimental import multihost_utils
    bits = np.frombuffer(uuid.uuid4().bytes[:8], dtype=np.int64).copy()
    bits = multihost_utils.broadcast_one_to_all(bits)
    return f"{int(bits[0]) & ((1 << 48) - 1):012x}"


def save_state(path, state, client_state=None, async_write=False,
               on_done=None):
    """Save `state` (a pytree of jax/np arrays). Each process writes only
    its addressable, replica-0 shards. Returns an AsyncCheckpointWriter
    when async_write (caller must .wait()), else None. ``on_done`` runs on
    process 0 after this process's shard file is durably written (the
    engine uses it to flip the ``latest`` pointer).

    Consistency: every save gets a fresh ``save_id``; shard files carry it
    in their name and the loader only reads files matching the meta's id.
    A crash mid-save therefore can never silently mix shard data from two
    saves — an interrupted save of an existing tag fails *loudly* at load
    (chunk-coverage error) instead of restoring stale weights under new
    step counters. Shard files are written to a .tmp name and renamed, so
    a half-written file never matches."""
    os.makedirs(path, exist_ok=True)
    names, leaves, _ = _flatten_named(state)
    save_id = _agree_save_id()

    if jax.process_index() == 0:
        meta = {
            "format": _FORMAT,
            "process_count": jax.process_count(),
            "save_id": save_id,
            "leaves": {
                name: {"shape": list(np.shape(leaf)),
                       "dtype": str(getattr(leaf, "dtype",
                                            np.asarray(leaf).dtype))}
                for name, leaf in zip(names, leaves)},
            "client_state": client_state or {},
        }
        tmp_meta = os.path.join(path, _META + ".tmp")
        with open(tmp_meta, "w") as f:
            json.dump(meta, f, indent=2, default=str)
        os.replace(tmp_meta, os.path.join(path, _META))

    shard_file = os.path.join(
        path, f"shards_p{jax.process_index():05d}.{save_id}.npz")

    # Snapshot device -> host synchronously: the caller's very next train
    # step donates optimizer buffers into XLA, so shard data must be read
    # before returning; only the (slow) file write happens on the thread.
    chunks = []
    for name, leaf in zip(names, leaves):
        for key, arr in _leaf_chunks(leaf):
            chunks.append((f"{name}|{key}", arr))

    # captured HERE, not inside write(): the async writer runs write()
    # on a worker thread where the caller's contextvar scope is gone
    tracer = tracing.current_tracer()

    def write():
        # fault point: a raised IOError here models a transient disk
        # failure — the supervisor's bounded-retry save path owns it
        faults.fire("ckpt.shard_write", path=shard_file)
        _t0 = time.monotonic()
        crcs = _write_npz_streaming(shard_file + ".tmp", chunks)
        os.replace(shard_file + ".tmp", shard_file)
        tracer.complete("ckpt_shard_write", _t0, time.monotonic(),
                        cat="ckpt", track="ckpt",
                        args={"file": os.path.basename(shard_file),
                              "chunks": len(chunks)})
        # fault point: actions here mangle the DURABLE file (truncation,
        # bit rot) so integrity verification and rollback are testable
        faults.fire("ckpt.shard_written", path=shard_file)
        if jax.process_index() == 0:
            # record per-entry CRC32s in the meta: verify_checkpoint and
            # the loader check entry bytes end-to-end against these (the
            # `latest` pointer only advances past this check). Process 0
            # knows only its own entries; other hosts' entries are still
            # covered by the zip-internal CRCs verify_checkpoint reads.
            _merge_meta_crcs(path, crcs)
        # reclaim this process's shard files from earlier saves of the tag
        me = f"shards_p{jax.process_index():05d}."
        for fn in os.listdir(path):
            if fn.startswith(me) and fn.endswith(".npz") and \
                    save_id not in fn:
                try:
                    os.remove(os.path.join(path, fn))
                except OSError:
                    pass
        # all hosts' shard files must be durable before the `latest`
        # pointer flips
        _t0 = time.monotonic()
        _durability_barrier(save_id, path, on_writer_thread=async_write)
        tracer.complete("ckpt_barrier", _t0, time.monotonic(),
                        cat="ckpt", track="ckpt",
                        args={"save_id": save_id})
        if on_done is not None and jax.process_index() == 0:
            on_done()

    if async_write:
        writer = AsyncCheckpointWriter(write)
        writer.start()
        return writer
    write()
    return None


def _merge_meta_crcs(path, crcs):
    """Fold this process's entry CRCs into checkpoint_meta.json
    (atomic rewrite; the meta body was written at save start)."""
    meta_f = os.path.join(path, _META)
    if not os.path.exists(meta_f):
        return
    with open(meta_f) as fh:
        meta = json.load(fh)
    merged = dict(meta.get("entry_crc32", {}))
    merged.update({k: int(v) for k, v in crcs.items()})
    meta["entry_crc32"] = merged
    tmp = meta_f + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2, default=str)
    os.replace(tmp, meta_f)


def verify_checkpoint(path):
    """Integrity-check one checkpoint tag directory WITHOUT restoring it.

    Returns ``(ok, problems)`` where ``problems`` is a list of strings.
    Checks, in order:

    1. the meta exists and parses;
    2. every process's shard file for the meta's ``save_id`` is present;
    3. each shard file is a structurally valid zip and every member's
       bytes match the zip-recorded CRC32 (catches truncation and bit
       corruption — ``testzip`` reads every byte);
    4. members named in the meta's ``entry_crc32`` map match it (catches
       a shard entry wholesale replaced with differently-valid bytes);
    5. the chunk rectangles cover every element of every leaf in the
       meta (catches a missing/partial shard — never a silent partial
       restore).

    This is the gate the supervisor runs before advancing the ``latest``
    pointer, and again (per candidate tag) when rolling back to the
    newest intact tag.
    """
    problems = []
    meta_f = os.path.join(path, _META)
    if not os.path.isdir(path):
        return False, [f"no such checkpoint directory: {path}"]
    if not os.path.exists(meta_f):
        if os.path.exists(os.path.join(path, "model_states.npz")):
            return True, []     # round-1 format: no integrity metadata
        return False, [f"missing {_META}"]
    try:
        with open(meta_f) as fh:
            meta = json.load(fh)
    except (json.JSONDecodeError, OSError) as e:
        return False, [f"unreadable {_META}: {e}"]
    save_id = meta.get("save_id")
    nprocs = int(meta.get("process_count", 1))
    meta_crcs = {k: int(v) for k, v in meta.get("entry_crc32", {}).items()}

    shard_files = []
    for fn in sorted(os.listdir(path)):
        if not (fn.startswith("shards_p") and fn.endswith(".npz")):
            continue
        stem = fn[len("shards_p"):-len(".npz")]
        _, _, fid = stem.partition(".")
        if save_id is not None and fid != save_id:
            continue
        shard_files.append(fn)
    if len(shard_files) < nprocs:
        problems.append(
            f"only {len(shard_files)}/{nprocs} shard files present for "
            f"save {save_id}")

    entry_crcs = {}     # member name (sans .npy) -> zip-recorded CRC
    for fn in shard_files:
        full = os.path.join(path, fn)
        try:
            with zipfile.ZipFile(full) as z:
                bad = z.testzip()   # full read: CRC of every member
                if bad is not None:
                    problems.append(f"{fn}: member {bad} fails CRC")
                for info in z.infolist():
                    key = info.filename[:-len(".npy")] \
                        if info.filename.endswith(".npy") else info.filename
                    entry_crcs[key] = info.CRC
        except (zipfile.BadZipFile, OSError) as e:
            problems.append(f"{fn}: unreadable/truncated zip ({e})")
    for key, want in meta_crcs.items():
        have = entry_crcs.get(key)
        if have is None:
            problems.append(f"meta entry {key} missing from shard files")
        elif have != want:
            problems.append(
                f"entry {key}: crc32 {have:#010x} != meta {want:#010x}")

    # chunk coverage per leaf (disjoint-rectangle volume accounting, the
    # same standard assemble() enforces at restore time)
    for name, info in (meta.get("leaves") or {}).items():
        shape = tuple(info.get("shape", ()))
        want = int(np.prod(shape)) if shape else 1
        filled = 0
        for key in entry_crcs:
            leaf, _, idx = key.rpartition("|")
            if leaf != name:
                continue
            if not idx:
                filled += 1
                continue
            vol = 1
            for part in idx.split(","):
                a, b = part.split(":")
                vol *= max(0, int(b) - int(a))
            filled += vol
        if filled < want:
            problems.append(
                f"leaf {name}: chunks cover {filled}/{want} elements")
    return not problems, problems


class AsyncCheckpointWriter:
    """Background-thread writer (the Nebula-checkpoint-engine capability:
    training resumes while the checkpoint drains to disk)."""

    def __init__(self, fn):
        self._err = None

        def run():
            try:
                fn()
            except BaseException as e:  # surfaced on wait()
                self._err = e

        self._thread = threading.Thread(target=run, daemon=True)

    def start(self):
        self._thread.start()

    def wait(self):
        self._thread.join()
        if self._err is not None:
            raise self._err


class _ChunkIndex:
    """Registry of all chunk rectangles across a checkpoint's shard files,
    with lazy (zip-entry-at-a-time) reads."""

    def __init__(self, path):
        self.path = path
        self.by_leaf = {}      # name -> list of (index_key, file, zip_name)
        self._files = {}
        self._verified = set()  # (file, entry) pairs already CRC-checked
        self.meta = None
        meta_f = os.path.join(path, _META)
        if os.path.exists(meta_f):
            with open(meta_f) as fh:
                self.meta = json.load(fh)
        nprocs = (self.meta or {}).get("process_count")
        save_id = (self.meta or {}).get("save_id")
        for fn in sorted(os.listdir(path)):
            if not (fn.startswith("shards_p") and fn.endswith(".npz")):
                continue
            stem = fn[len("shards_p"):-len(".npz")]
            pidx, _, fid = stem.partition(".")
            if save_id is not None and fid != save_id:
                continue  # stale file from a different save of this tag
            if nprocs is not None and int(pidx) >= nprocs:
                continue  # stale file from an older, wider save
            full = os.path.join(path, fn)
            npz = np.load(full, allow_pickle=False)
            self._files[fn] = npz
            for zkey in npz.files:
                name, _, idx = zkey.rpartition("|")
                self.by_leaf.setdefault(name, []).append((idx, fn, zkey))

    def saved_shape(self, name):
        """Authoritative global shape from the meta (falls back to chunk
        max-stops for meta-less checkpoints)."""
        leaves = (self.meta or {}).get("leaves", {})
        if name in leaves:
            return tuple(leaves[name]["shape"])
        return self.leaf_shape(name)

    def names(self):
        return list(self.by_leaf)

    def leaf_shape(self, name):
        stops = None
        for idx, _, _ in self.by_leaf[name]:
            sls = [p.split(":") for p in idx.split(",")] if idx else []
            ends = [int(b) for _, b in sls]
            stops = ends if stops is None else \
                [max(a, b) for a, b in zip(stops, ends)]
        return tuple(stops or ())

    def read(self, fn, zkey):
        """Read one entry, verifying its bytes against the meta-recorded
        CRC32 on first access ("verified at load"): corruption raises
        :class:`CheckpointCorrupt` instead of restoring garbage."""
        crcs = (self.meta or {}).get("entry_crc32") or {}
        want = crcs.get(zkey)
        if want is not None and (fn, zkey) not in self._verified:
            raw = self._files[fn].zip.read(zkey + ".npy")
            have = zlib.crc32(raw)
            if have != int(want):
                raise CheckpointCorrupt(
                    f"checkpoint entry {zkey} in {fn}: crc32 "
                    f"{have:#010x} != recorded {int(want):#010x} — "
                    f"shard data corrupt; roll back to an intact tag")
            self._verified.add((fn, zkey))
            return np.lib.format.read_array(io.BytesIO(raw),
                                            allow_pickle=False)
        return self._files[fn][zkey]

    def _saved_dtype(self, name):
        """The dtype the leaf was saved with (None when meta-less)."""
        info = (self.meta or {}).get("leaves", {}).get(name)
        if info and "dtype" in info:
            try:
                import ml_dtypes
                return np.dtype(getattr(ml_dtypes, info["dtype"],
                                        info["dtype"]))
            except TypeError:
                return None
        return None

    def _decode_chunk(self, name, chunk):
        """Undo the uint-bits storage of ml_dtypes leaves (see
        _write_npz_streaming): re-view from the meta's recorded dtype;
        meta-less V2 entries (pre-fix checkpoints) best-effort as bf16."""
        saved = self._saved_dtype(name)
        if saved is not None and saved.kind == "V" and \
                chunk.dtype.kind in "ui" and \
                chunk.dtype.itemsize == saved.itemsize:
            return chunk.view(saved)
        if chunk.dtype.kind == "V" and chunk.dtype.itemsize == 2:
            import ml_dtypes
            return chunk.view(ml_dtypes.bfloat16)
        return chunk

    def assemble(self, name, out_index, shape, dtype):
        """Build the sub-array `out_index` (tuple of concrete slices) of
        leaf `name` from whatever chunk rectangles overlap it."""
        out_shape = tuple(sl.stop - sl.start for sl in out_index)
        out = np.empty(out_shape, dtype)
        filled = 0
        for idx_key, fn, zkey in self.by_leaf[name]:
            cidx = _parse_index(idx_key)
            inter = []
            for o, c in zip(out_index, cidx):
                lo, hi = max(o.start, c.start), min(o.stop, c.stop)
                if lo >= hi:
                    inter = None
                    break
                inter.append((lo, hi))
            if inter is None and len(out_index) > 0:
                continue
            chunk = self._decode_chunk(name, self.read(fn, zkey))
            if not out_index:  # scalar (stored as (1,), see writer)
                return chunk.reshape(()).astype(dtype)
            dst = tuple(slice(lo - o.start, hi - o.start)
                        for (lo, hi), o in zip(inter, out_index))
            src = tuple(slice(lo - c.start, hi - c.start)
                        for (lo, hi), c in zip(inter, cidx))
            out[dst] = chunk[src].astype(dtype)
            filled += int(np.prod([hi - lo for lo, hi in inter]))
        want = int(np.prod(out_shape))
        if filled < want:
            raise ValueError(
                f"checkpoint chunks cover {filled}/{want} elements of "
                f"{name}{out_index} — missing shard files?")
        return out

    def close(self):
        for npz in self._files.values():
            npz.close()


def _normalize_index(index, shape):
    return tuple(slice(0 if sl.start is None else int(sl.start),
                       dim if sl.stop is None else int(sl.stop))
                 for sl, dim in zip(index, shape))


def _restore_leaf(chunks, key, leaf):
    """Rebuild one leaf onto the target's sharding, reading only the
    slices the local devices need."""
    shape = tuple(np.shape(leaf))
    dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and shape:
        def cb(index):
            return chunks.assemble(key, _normalize_index(index, shape),
                                   shape, dtype)
        return jax.make_array_from_callback(shape, sharding, cb)
    full = chunks.assemble(key, _full_index(shape), shape, dtype)
    if sharding is not None:  # scalar jax array
        return jax.device_put(full, sharding)
    return full


def _load_format1(path, target, prefix):
    """Back-compat: round-1 single-file .npz checkpoints."""
    data = np.load(os.path.join(path, "model_states.npz"),
                   allow_pickle=False)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    new = []
    for path_k, leaf in flat:
        key = prefix + jax.tree_util.keystr(path_k)
        if key not in data:
            raise KeyError(f"checkpoint missing entry {key}")
        arr = data[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for {key}: checkpoint "
                             f"{arr.shape} vs target {np.shape(leaf)}")
        sharding = getattr(leaf, "sharding", None)
        dtype = getattr(leaf, "dtype", arr.dtype)
        new.append(jax.device_put(arr.astype(dtype), sharding)
                   if sharding is not None else arr)
    return jax.tree_util.tree_unflatten(treedef, new)


def load_subtree(path, target, prefix=""):
    """Restore a subtree of a saved state into `target` (same structure),
    re-applying each target leaf's sharding/dtype. `prefix` addresses the
    subtree inside the saved pytree (e.g. ".params") — the engine-side
    half of the reference's universal-checkpoint param-fragment loading
    (deepspeed/checkpoint/universal_checkpoint.py:12)."""
    if not os.path.exists(os.path.join(path, _META)):
        return _load_format1(path, target, prefix)
    chunks = _ChunkIndex(path)
    try:
        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        new = []
        for path_k, leaf in flat:
            key = prefix + jax.tree_util.keystr(path_k)
            if key not in chunks.by_leaf:
                raise KeyError(f"checkpoint missing entry {key}")
            saved = chunks.saved_shape(key)
            if tuple(saved) != tuple(np.shape(leaf)):
                raise ValueError(f"shape mismatch for {key}: checkpoint "
                                 f"{saved} vs target {np.shape(leaf)}")
            new.append(_restore_leaf(chunks, key, leaf))
        return jax.tree_util.tree_unflatten(treedef, new)
    finally:
        chunks.close()


def load_state(path, target_state, mesh=None):
    """Restore into the structure/shardings of `target_state`; returns
    (state, client_state). The saving run's mesh/ZeRO layout is irrelevant
    — chunks are globally indexed."""
    state = load_subtree(path, target_state, prefix="")
    client = {}
    meta_f = os.path.join(path, _META)
    if os.path.exists(meta_f):
        with open(meta_f) as fh:
            client = json.load(fh).get("client_state", {})
    else:
        cs = os.path.join(path, "client_state.json")
        if os.path.exists(cs):
            with open(cs) as fh:
                client = json.load(fh)
    return state, client


def consolidate(path, out_file, prefix=".params", dtype=np.float32):
    """zero_to_fp32 equivalent (reference utils/zero_to_fp32.py:313):
    stream-merge a sharded checkpoint's param leaves into one fp32 .npz,
    one leaf in memory at a time. Prefers the ZeRO-Offload fp32 master
    copy when present (it is the authoritative high-precision state)."""
    if not os.path.exists(os.path.join(path, _META)) and \
            os.path.exists(os.path.join(path, "model_states.npz")):
        # round-1 single-file checkpoints
        with np.load(os.path.join(path, "model_states.npz"),
                     allow_pickle=False) as d:
            def f1_iter():
                for k in d.files:
                    if k.startswith(prefix):
                        yield k, d[k].astype(dtype)
            _write_npz_streaming(out_file, f1_iter())
        return out_file
    chunks = _ChunkIndex(path)
    master_npz = None
    try:
        # tree order, as recorded in the meta (matches the offload
        # optimizer's master_{i} flat-leaf numbering)
        if chunks.meta is not None:
            names = [n for n in chunks.meta["leaves"] if n.startswith(prefix)]
        else:
            names = [n for n in chunks.names() if n.startswith(prefix)]
        if not names:
            raise ValueError(f"no leaves under {prefix!r} in {path}")
        master_of = {}          # name -> master_{i} key, read lazily
        host_opt = os.path.join(path, "host_optim_states.npz")
        if os.path.exists(host_opt):
            master_npz = np.load(host_opt, allow_pickle=False)
            n_master = sum(1 for k in master_npz.files
                           if k.startswith("master_"))
            if "leaf_names" in master_npz.files:
                # authoritative pairing: the offload optimizer records its
                # flat-leaf order by checkpoint name
                saved_names = [str(s) for s in master_npz["leaf_names"]]
                known = set(names)
                master_of = {name: f"master_{i}"
                             for i, name in enumerate(saved_names)
                             if name in known}
            elif n_master == len(names):
                master_of = {name: f"master_{i}"
                             for i, name in enumerate(names)}

        def leaf_iter():
            for name in names:
                shape = chunks.saved_shape(name)
                if name in master_of:
                    flat = master_npz[master_of[name]]
                    if flat.size != int(np.prod(shape)):
                        raise ValueError(
                            f"host master entry {master_of[name]} has "
                            f"{flat.size} elements but leaf {name} has "
                            f"shape {shape} — offload state and model "
                            "meta disagree")
                    arr = flat.reshape(shape).astype(dtype)
                else:
                    arr = chunks.assemble(name, _full_index(shape), shape,
                                          dtype)
                yield name, arr
        _write_npz_streaming(out_file, leaf_iter())
    finally:
        if master_npz is not None:
            master_npz.close()
        chunks.close()
    return out_file
