"""Pluggable checkpoint-engine backends.

Reference: ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py:9``
— the ``CheckpointEngine`` ABC (create/save/load/commit) with swappable
backends (TorchCheckpointEngine, the Nebula async engine). The TPU
repo's native format is the sharded npz-chunk layout in
``checkpoint/engine.py``; this module is the SEAM that lets a
deployment swap it (e.g. a GCS/tensorstore backend on pods, where
checkpoints should stream to object storage rather than a filesystem)
without touching DeepSpeedEngine.

Select via config::

    {"checkpoint_engine": {"type": "npz"}}                      # default
    {"checkpoint_engine": {"type": "my_pkg.my_mod:MyEngine",
                           "params": {...}}}                    # custom

A custom class implements :class:`CheckpointEngine`; ``save`` may
return a writer object with ``wait()`` for async backends (the engine
calls ``wait_checkpoint`` through it, same contract as the native
async writer).

Known seam limit: the training engine's AUXILIARY artifacts — host
optimizer states under ZeRO-Offload (``host_optim_states.npz``) and
the 16-bit consolidation file — still write as numpy files next to the
backend's payload; a fully remote backend must handle (or disable)
those paths.
"""

import abc

from deepspeed_tpu.utils.logging import logger


class CheckpointEngine(abc.ABC):
    """The backend contract DeepSpeedEngine saves/loads through."""

    def __init__(self, params=None):
        self.params = dict(params or {})

    def create(self, tag):
        """Hook before a save of ``tag`` begins (reference: logging /
        transaction open)."""

    @abc.abstractmethod
    def save(self, path, state, client_state=None, async_write=False,
             on_done=None):
        """Persist ``state`` (pytree) + ``client_state`` under ``path``.
        Returns None or an async writer exposing ``wait()``."""

    @abc.abstractmethod
    def load(self, path, target, mesh=None):
        """Restore into ``target``'s structure/shardings; returns
        (state, client_state)."""

    def load_subtree(self, path, target, prefix):
        """Partial restore (inference engines pull only ``.params``);
        backends that cannot do better may load everything and slice."""
        raise NotImplementedError

    def commit(self, tag):
        """Hook after the save of ``tag`` is durable (reference: the
        Nebula engine publishes the checkpoint here)."""


class NpzCheckpointEngine(CheckpointEngine):
    """The native sharded npz-chunk format (checkpoint/engine.py):
    per-process shard files, async writer thread, durability barrier,
    reshape-on-load across mesh/stage changes."""

    def save(self, path, state, client_state=None, async_write=False,
             on_done=None):
        from deepspeed_tpu.checkpoint.engine import save_state
        return save_state(path, state, client_state,
                          async_write=async_write, on_done=on_done)

    def load(self, path, target, mesh=None):
        from deepspeed_tpu.checkpoint.engine import load_state
        return load_state(path, target, mesh=mesh)

    def load_subtree(self, path, target, prefix):
        from deepspeed_tpu.checkpoint.engine import load_subtree
        return load_subtree(path, target, prefix=prefix)


def get_checkpoint_engine(section):
    """``checkpoint_engine`` config section -> backend instance."""
    section = dict(section or {})
    kind = section.get("type", "npz")
    params = section.get("params") or {}
    if kind in ("npz", "native", "default"):
        return NpzCheckpointEngine(params)
    if ":" not in kind:
        raise ValueError(
            f"checkpoint_engine.type {kind!r}: use 'npz' or a "
            "'package.module:ClassName' path to a CheckpointEngine "
            "subclass")
    mod_name, cls_name = kind.split(":", 1)
    import importlib
    cls = getattr(importlib.import_module(mod_name), cls_name)
    engine = cls(params)
    assert isinstance(engine, CheckpointEngine), \
        f"{kind} is not a CheckpointEngine"
    logger.info(f"checkpoint engine: {kind}")
    return engine
