"""Pluggable checkpoint-engine backends.

Reference: ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py:9``
— the ``CheckpointEngine`` ABC (create/save/load/commit) with swappable
backends (TorchCheckpointEngine, the Nebula async engine). The TPU
repo's native format is the sharded npz-chunk layout in
``checkpoint/engine.py``; this module is the SEAM that lets a
deployment swap it (e.g. a GCS/tensorstore backend on pods, where
checkpoints should stream to object storage rather than a filesystem)
without touching DeepSpeedEngine.

Select via config::

    {"checkpoint_engine": {"type": "npz"}}                      # default
    {"checkpoint_engine": {"type": "my_pkg.my_mod:MyEngine",
                           "params": {...}}}                    # custom

A custom class implements :class:`CheckpointEngine`; ``save`` may
return a writer object with ``wait()`` for async backends (the engine
calls ``wait_checkpoint`` through it, same contract as the native
async writer).

The engine routes EVERY checkpoint artifact through the backend: the
main sharded state, the ZeRO-Offload host optimizer states
(``save_aux``/``load_aux`` — streamed entry by entry, so the
ZeRO-Infinity tier never materializes a model-sized dict), and the
16-bit consolidation (``consolidate_16bit``). A remote backend
overrides those three to own all bytes.
"""

import abc
import contextlib

from deepspeed_tpu.utils.logging import logger


class CheckpointEngine(abc.ABC):
    """The backend contract DeepSpeedEngine saves/loads through."""

    def __init__(self, params=None):
        self.params = dict(params or {})

    def create(self, tag):
        """Hook before a save of ``tag`` begins (reference: logging /
        transaction open)."""

    @abc.abstractmethod
    def save(self, path, state, client_state=None, async_write=False,
             on_done=None):
        """Persist ``state`` (pytree) + ``client_state`` under ``path``.
        Returns None or an async writer exposing ``wait()``."""

    @abc.abstractmethod
    def load(self, path, target, mesh=None):
        """Restore into ``target``'s structure/shardings; returns
        (state, client_state)."""

    def load_subtree(self, path, target, prefix):
        """Partial restore (inference engines pull only ``.params``);
        backends that cannot do better may load everything and slice."""
        raise NotImplementedError

    def save_aux(self, path, name, entries):
        """Persist an auxiliary artifact (ZeRO-Offload host optimizer
        states). ``entries`` is an ITERATOR of (key, np.ndarray) —
        consume it streaming; materializing it defeats the ZeRO-Infinity
        RAM bound. Default: the native streamed-npz file, so existing
        custom backends keep working; remote backends override."""
        import os
        from deepspeed_tpu.checkpoint.engine import _write_npz_streaming
        _write_npz_streaming(os.path.join(path, name + ".npz"), entries)

    @contextlib.contextmanager
    def load_aux(self, path, name):
        """Context manager yielding a lazy mapping of the artifact's
        entries, or None when absent."""
        import os
        import numpy as np
        full = os.path.join(path, name + ".npz")
        if not os.path.exists(full):
            yield None
            return
        with np.load(full) as d:    # lazy NpzFile: one entry at a time
            yield d

    def consolidate_16bit(self, path, out_name, dtype):
        """Emit the gathered 16-bit weights artifact from the durable
        checkpoint at ``path`` (reference
        zero_gather_16bit_weights_on_model_save, engine.py:754).
        Default: the native consolidate over the npz chunks."""
        import os
        from deepspeed_tpu.checkpoint.engine import consolidate
        consolidate(path, os.path.join(path, out_name), dtype=dtype)

    def commit(self, tag):
        """Hook after the save of ``tag`` is durable (reference: the
        Nebula engine publishes the checkpoint here)."""


class NpzCheckpointEngine(CheckpointEngine):
    """The native sharded npz-chunk format (checkpoint/engine.py):
    per-process shard files, async writer thread, durability barrier,
    reshape-on-load across mesh/stage changes."""

    def save(self, path, state, client_state=None, async_write=False,
             on_done=None):
        from deepspeed_tpu.checkpoint.engine import save_state
        return save_state(path, state, client_state,
                          async_write=async_write, on_done=on_done)

    def load(self, path, target, mesh=None):
        from deepspeed_tpu.checkpoint.engine import load_state
        return load_state(path, target, mesh=mesh)

    def load_subtree(self, path, target, prefix):
        from deepspeed_tpu.checkpoint.engine import load_subtree
        return load_subtree(path, target, prefix=prefix)

def get_checkpoint_engine(section):
    """``checkpoint_engine`` config section -> backend instance."""
    section = dict(section or {})
    kind = section.get("type", "npz")
    params = section.get("params") or {}
    if kind in ("npz", "native", "default"):
        return NpzCheckpointEngine(params)
    if ":" not in kind:
        raise ValueError(
            f"checkpoint_engine.type {kind!r}: use 'npz' or a "
            "'package.module:ClassName' path to a CheckpointEngine "
            "subclass")
    mod_name, cls_name = kind.split(":", 1)
    import importlib
    cls = getattr(importlib.import_module(mod_name), cls_name)
    engine = cls(params)
    assert isinstance(engine, CheckpointEngine), \
        f"{kind} is not a CheckpointEngine"
    logger.info(f"checkpoint engine: {kind}")
    return engine
