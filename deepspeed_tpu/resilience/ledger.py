"""Goodput ledger: classify every wall-clock second of a supervised run.

Large-scale training reports (MegaScale's straggler diagnosis, Google's
"goodput" accounting for ML SLOs) treat *time attribution* as the
first-class production metric: of the wall time a job held its chips,
how much produced new optimizer steps, and where did the rest go?  The
ledger answers that for a ``ResilientTrainer.train()`` run — including
one interrupted and resumed across process incarnations — with six
categories that always partition 100% of the measured wall time:

``productive``
    First-time train steps: ``global_steps`` advanced past the furthest
    step this run had ever reached.
``compile_warmup``
    Steps during which the engine compiled a new executable (detected
    via ``engine.train_compile_counts()`` deltas — every incarnation
    pays this again, which is exactly the point of measuring it).
``checkpoint_stall``
    Wall time blocked inside the supervisor's ``save()`` (shard write,
    post-save verification, retention rotation, retries).
``recompute``
    Re-running steps that an earlier incarnation (or a pre-rollback
    present) had already completed — the price of restoring an older
    checkpoint after a crash or corruption rollback.
``divergence_retry``
    NaN-watchdog handling: the rollback restore itself (the re-run
    steps afterwards count as ``recompute``).
``idle``
    Everything else inside the ``train()`` wall: data loading, host
    bookkeeping, the preemption drain, gauge emission.  Computed as
    the remainder, which is what guarantees the partition.

Accounting is **segment-based**: ``begin()`` opens a wall segment (one
``train()`` call), ``add(category, seconds)`` attributes measured
sub-intervals, ``finish()`` closes the segment and sweeps the
unattributed remainder into ``idle``.  Totals accumulate across
segments and across incarnations (the supervisor persists
``snapshot()`` into ``run_state.json`` every step and seeds the next
incarnation's ledger with it via ``carry``), so ``fractions()`` over a
resumed run partitions the *sum of all incarnations'* train() wall
time.
"""

import time

CATEGORIES = ("productive", "compile_warmup", "checkpoint_stall",
              "recompute", "divergence_retry", "idle")


class GoodputLedger:
    def __init__(self, carry=None):
        self.seconds = {c: 0.0 for c in CATEGORIES}
        if carry:
            for c in CATEGORIES:
                self.seconds[c] += float(carry.get(c, 0.0))
        self._t0 = None          # open segment start (monotonic)
        self._attributed = 0.0   # seconds attributed inside the segment

    @property
    def active(self):
        return self._t0 is not None

    def begin(self):
        """Open a wall segment (one train() call)."""
        self._t0 = time.monotonic()
        self._attributed = 0.0

    def add(self, category, seconds):
        """Attribute ``seconds`` of the open segment to ``category``."""
        if category not in self.seconds:
            raise ValueError(f"unknown goodput category {category!r}")
        seconds = max(0.0, float(seconds))
        self.seconds[category] += seconds
        if self._t0 is not None:
            self._attributed += seconds

    def finish(self):
        """Close the segment: the unattributed remainder is idle time.
        (Attribution can only under-count — every add() is a measured
        sub-interval of the segment — so the remainder is >= 0 up to
        clock granularity and the categories partition the wall.)"""
        if self._t0 is None:
            return
        wall = time.monotonic() - self._t0
        self.seconds["idle"] += max(0.0, wall - self._attributed)
        self._t0 = None
        self._attributed = 0.0

    # ------------------------------------------------------- exporting
    def snapshot(self):
        """Crash-durable totals: category seconds as if the segment
        ended now (idle-so-far included, nothing mutated).  What the
        supervisor persists per step so a SIGKILLed incarnation still
        hands its wall time to the next one."""
        out = dict(self.seconds)
        if self._t0 is not None:
            wall = time.monotonic() - self._t0
            out["idle"] += max(0.0, wall - self._attributed)
        return out

    def wall_s(self):
        return sum(self.snapshot().values())

    def fractions(self):
        snap = self.snapshot()
        total = sum(snap.values())
        if total <= 0.0:
            return {c: 0.0 for c in CATEGORIES}
        return {c: snap[c] / total for c in CATEGORIES}

    def as_dict(self):
        snap = self.snapshot()
        total = sum(snap.values())
        return {"wall_s": total, "seconds": snap,
                "fractions": self.fractions()}
