"""Training supervision: a self-healing wrapper around DeepSpeedEngine.

``ResilientTrainer`` owns the failure modes a long preemptible-capacity
run actually dies from (Bamboo, NSDI '23; the reference's elastic
training + Nebula tiered checkpoints):

* **Preemption** — SIGTERM sets a flag, the in-flight step finishes,
  a checkpoint is saved, and ``train()`` returns cleanly with status
  ``"preempted"`` (the contract ``elasticity/elastic_agent.py``'s
  graceful ``terminate()`` relies on).
* **Periodic checkpointing** with retention/rotation, where the
  ``latest`` pointer only advances after
  :func:`~deepspeed_tpu.checkpoint.engine.verify_checkpoint` passes —
  a crash can leave a torn tag on disk but never a ``latest`` that
  points at one.
* **Rollback** — ``resume()`` walks tags newest-first, verifying each,
  and restores the newest *intact* one; corrupt tags are quarantined
  (renamed ``<tag>.corrupt``) so they are never retried. A restore is
  all-or-nothing: the engine's state is only replaced after the full
  tree loads, so a corrupt shard can never leave a partial mix.
* **Transient save failures** — bounded retry with exponential backoff
  (each attempt is a fresh ``save_id``, so a half-written attempt can
  never contaminate the retry).
* **NaN/divergence watchdog** — a non-finite loss is skipped-and-logged
  or rolled back to the last good checkpoint, per policy, with a
  bounded budget before the run halts loudly.

Observability (docs/observability.md, "Training-tier"):

* **Step spans** — with a :class:`~deepspeed_tpu.tracing.SpanTracer`
  installed (``tracer=`` / ``trace_dir=``), every train step, data
  fetch, checkpoint save/verify/rotate, resume/rollback and the
  preemption drain records host-side spans; the engine adds
  ``fwd_bwd_dispatch`` / ``device_wait`` / ``optimizer_step`` /
  ``grad_sync`` (and per-micro tracks under gas>1).  Spans persist per
  *incarnation* under ``<save_dir>/trace/`` and
  :func:`merge_train_trace` merges every incarnation of one run —
  identified by the ``run_id`` persisted in ``run_state.json``, which
  survives SIGTERM/crash — into a single Chrome/Perfetto JSON.
* **Goodput ledger** — every wall second of ``train()`` classified into
  :data:`~deepspeed_tpu.resilience.ledger.CATEGORIES` (productive /
  compile_warmup / checkpoint_stall / recompute / divergence_retry /
  idle), cumulative across incarnations, exported in ``TrainReport``,
  the monitor stream (``train/goodput/*``) and
  :meth:`prometheus_text`.
* **Live MFU / throughput gauges** — per-window ``train/mfu``,
  ``train/tokens_per_s``, ``train/tflops_achieved`` and
  ``train/step_time_ms`` monitor events from the flops-profiler model
  estimate + measured wall time.
* **Stall/straggler watchdog** — an EWMA step-time anomaly emits
  ``train/straggler`` (+ a flight-recorder dump); a no-progress timer
  (``stall_timeout_s``) emits ``train/stall`` and dumps the recent
  span window while the process is still alive to be debugged.

All events flow through ``monitor/`` (``resilience/*`` + ``train/*``
tags, the unified taxonomy in :data:`deepspeed_tpu.tracing.
EVENT_TAXONOMY`) and are kept in an in-memory
:class:`~deepspeed_tpu.monitor.monitor.RingBufferMonitor` for
``status()`` introspection.

Every recovery path here is covered by the deterministic fault harness
(:mod:`deepspeed_tpu.resilience.faults`) in
``tests/unit/test_resilience.py``; the observability layer by
``tests/unit/test_train_trace.py``.
"""

import dataclasses
import json
import os
import re
import signal
import threading
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import tracing
from deepspeed_tpu.checkpoint.engine import (CheckpointCorrupt,
                                             verify_checkpoint)
from deepspeed_tpu.monitor.monitor import RingBufferMonitor
from deepspeed_tpu.resilience.ledger import CATEGORIES, GoodputLedger
from deepspeed_tpu.tracing import (NULL_TRACER, SpanTracer, merge_chrome,
                                   prometheus_text)
from deepspeed_tpu.utils.logging import logger


class Preempted(RuntimeError):
    """A preemption notice (SIGTERM) interrupted training; state was
    checkpointed and the process should exit cleanly."""


class DivergenceError(RuntimeError):
    """The NaN/divergence watchdog exhausted its recovery budget."""


@dataclasses.dataclass
class TrainReport:
    """What happened during one supervised ``train()`` call."""
    status: str = "completed"       # completed | preempted
    steps: int = 0                  # train_batch calls that ran
    last_loss: float = float("nan")
    nan_events: int = 0
    restores: int = 0               # watchdog rollbacks
    saves: int = 0                  # checkpoints that passed verification
    save_retries: int = 0           # failed save attempts that were retried
    resumed_from: str = None        # tag resume() restored, if any
    preempted_at_step: int = None
    run_id: str = None              # persisted run identity (run_state.json)
    incarnation: int = 0            # 1-based process incarnation of the run
    stragglers: int = 0             # EWMA step-time anomalies
    stalls: int = 0                 # no-progress watchdog firings
    mfu: float = None               # last gauge-window MFU (if measurable)
    tokens_per_s: float = None      # last gauge-window token throughput
    ledger: dict = None             # goodput ledger (cumulative for the run)


def merge_train_trace(trace_dir, out=None):
    """Merge every incarnation's flushed span file
    (``spans_inc*.jsonl``, one serialized event per line — append-only
    so flushing costs O(new spans), not O(run history)) under
    ``trace_dir`` into ONE Chrome-trace JSON — the single timeline of a
    run that crossed process boundaries (each incarnation is a Perfetto
    *process*; all share the run id in their process names).  Returns
    the output path (default ``<trace_dir>/train_trace.json``)."""
    lists = []
    for name in sorted(os.listdir(trace_dir)):
        if name.startswith("spans_inc") and name.endswith(".jsonl"):
            events = []
            try:
                with open(os.path.join(trace_dir, name)) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            events.append(json.loads(line))
            except (OSError, ValueError) as e:
                # a torn tail line (SIGKILL mid-append) drops that line
                # only; everything parsed before it is kept
                logger.warning(f"partial span file {name}: {e}")
            if events:
                lists.append(events)
    trace = merge_chrome(lists)
    out = out or os.path.join(trace_dir, "train_trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    return out


class _NoProgressWatchdog(threading.Thread):
    """Fires once per stall episode when no train step has completed
    for ``stall_timeout_s`` — dumping the flight record while the hung
    process is still alive is the whole point (a SIGKILLed hang leaves
    nothing).  Arms after the incarnation's FIRST completed step: the
    first step legitimately stalls for however long XLA compilation
    takes, and a timeout sized for steady-state steps would fire on
    every cold start."""

    def __init__(self, sup):
        super().__init__(daemon=True, name="ds-train-stall-watchdog")
        self.sup = sup
        self._stop_ev = threading.Event()

    def run(self):
        timeout = float(self.sup.stall_timeout_s)
        poll = max(0.01, min(timeout / 4.0, 1.0))
        while not self._stop_ev.wait(poll):
            sup = self.sup
            if sup.report.steps < 1:
                continue        # not armed until compile/warmup is paid
            if sup._watchdog_paused:
                continue        # a long save or restore is
                # checkpoint_stall / divergence_retry (visible as the
                # ckpt_save / resume spans and ledger categories), not
                # a training hang — firing here would burn the bounded
                # dump budget on false positives
            stuck = time.monotonic() - sup._progress_beat
            if stuck > timeout and not sup._stall_fired:
                sup._stall_fired = True
                sup.report.stalls += 1
                step = sup.engine.global_steps
                logger.warning(
                    f"no train-step progress for {stuck:.1f}s "
                    f"(step {step}); dumping flight record")
                sup._emit_events([("train/stall", stuck, step)])
                sup.tracer.instant("stall", cat="train", track="steps",
                                   args={"stuck_s": round(stuck, 3),
                                         "step": step})
                if sup.flight_recorder is not None:
                    sup.flight_recorder.dump(
                        f"train_stall_step{step}",
                        extra={"stuck_s": stuck, "step": step})

    def stop(self):
        self._stop_ev.set()
        self.join(timeout=2.0)


class ResilientTrainer:
    """Supervised training loop over a ``DeepSpeedEngine``.

    Args:
        engine: a live ``DeepSpeedEngine``.
        save_dir: checkpoint root (tags are subdirectories).
        save_interval: save every N optimizer steps (0 = only on
            preemption / explicit ``save()``).
        keep_last: retention — newest N verified tags are kept, older
            ones rotate out (the tag ``latest`` points to is never
            removed).
        save_retries: attempts per save before giving up.
        retry_backoff_s: base backoff; doubles per failed attempt.
        nan_policy: ``"restore"`` (roll back to last good checkpoint),
            ``"skip"`` (log and continue), or ``"halt"``.
        max_nan_events: recovery budget — restores (restore policy) or
            consecutive NaN steps (skip policy) beyond this raise
            :class:`DivergenceError`.
        monitor: optional extra ``write_events`` sink; the engine's own
            monitor (when enabled) and the internal ring buffer always
            receive events.
        signals: signals treated as preemption notices during
            ``train()`` (default: SIGTERM).
        preemption_grace_s: wall-time budget for the preemption save
            (the SIGTERM-to-SIGKILL window). Defaults to the
            ``DS_PREEMPTION_GRACE_S`` env var the elastic agent
            publishes; None means unbounded.
        tracer: a :class:`~deepspeed_tpu.tracing.SpanTracer` (installed
            into the engine too); None disables tracing unless
            ``trace_dir`` is set, in which case one is created.
        trace_dir: directory for per-incarnation span files + the
            merged ``train_trace.json`` (default ``<save_dir>/trace``
            when tracing is on).
        flight_recorder: a :class:`~deepspeed_tpu.tracing.
            FlightRecorder`; the supervisor registers its tracer and
            dumps on stall, straggler, divergence rollback,
            checkpoint-corruption rollback and preemption.
        stall_timeout_s: no-progress watchdog timeout (None = off).
        straggler_factor: EWMA step-time anomaly threshold (a step
            slower than ``factor x EWMA`` after warmup is a straggler).
        gauge_interval: emit throughput/MFU/goodput monitor gauges
            every N steps (0 = off).
        mfu_gauge: include MFU/TFLOPS in the gauges (the first window
            pays one XLA cost-analysis of the compiled step to learn
            the model flops; tokens/s and step-time gauges are free).
        peak_flops_per_device: override the per-device peak-flops
            estimate used by the MFU gauge (default: autodetected per
            device kind; a nominal 1e12 off-TPU, matching bench.py).
    """

    def __init__(self, engine, save_dir, *, save_interval=0, keep_last=3,
                 tag_prefix="step", save_retries=3, retry_backoff_s=0.25,
                 nan_policy="restore", max_nan_events=3,
                 monitor=None, signals=(signal.SIGTERM,),
                 preemption_grace_s=None,
                 tracer=None, trace_dir=None, flight_recorder=None,
                 stall_timeout_s=None, straggler_factor=3.0,
                 gauge_interval=8, mfu_gauge=True,
                 peak_flops_per_device=None, compile_watchdog=None):
        if nan_policy not in ("restore", "skip", "halt"):
            raise ValueError(f"unknown nan_policy {nan_policy!r}")
        self.engine = engine
        self.save_dir = str(save_dir)
        self.save_interval = int(save_interval)
        self.keep_last = int(keep_last)
        self.tag_prefix = tag_prefix
        self.save_retries = int(save_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # SIGTERM-to-SIGKILL window (elastic_agent's term_grace_s, which
        # it publishes as DS_PREEMPTION_GRACE_S): the preemption save
        # must not retry-and-backoff past the point where the agent
        # escalates to SIGKILL and tears the write mid-file anyway
        if preemption_grace_s is None:
            env = os.environ.get("DS_PREEMPTION_GRACE_S")
            preemption_grace_s = float(env) if env else None
        self.preemption_grace_s = preemption_grace_s
        self.nan_policy = nan_policy
        self.max_nan_events = int(max_nan_events)
        self.ring = RingBufferMonitor()
        self._extra_monitor = monitor
        self.signals = tuple(signals)
        self._preempt_requested = False
        self._old_handlers = {}
        self.report = TrainReport()

        # ------------------------------- run identity (cross-incarnation)
        # run_state.json survives SIGTERM/crash: the run id keys the
        # merged trace, max_step_reached keys recompute attribution, and
        # the ledger carry keeps the goodput partition cumulative across
        # process incarnations.  Written atomically every step (cheap
        # next to any train step) so even a SIGKILL loses at most the
        # in-flight step's attribution.
        self._run_state_path = os.path.join(self.save_dir, "run_state.json")
        st = self._read_run_state()
        self._had_run_state = bool(st)
        self.run_id = st.get("run_id") or uuid.uuid4().hex[:12]
        self.incarnation = int(st.get("incarnations", 0))
        self._max_step_reached = int(st.get("max_step_reached", 0))
        self.ledger = GoodputLedger(carry=st.get("ledger"))

        # ------------------------------------------------------ tracing
        if tracer is None and trace_dir is not None:
            tracer = SpanTracer(process="train", capacity=32768)
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.trace_dir = trace_dir or (
            os.path.join(self.save_dir, "trace")
            if self.tracer.enabled else None)
        self._trace_flushed_total = (
            self.tracer.dropped + len(self.tracer.events))
        self.flight_recorder = flight_recorder
        if flight_recorder is not None and self.tracer.enabled:
            flight_recorder.register(f"train:{self.run_id}", self.tracer)
        if hasattr(engine, "set_tracer"):
            engine.set_tracer(self.tracer)

        # ------------------------------------------ watchdogs and gauges
        # recompile watchdog (tracing.CompileWatchdog, shared with the
        # serving tier): train-step compile deltas — the same
        # train_compile_count() probe the goodput ledger's
        # compile_warmup category keys on — become `compile` spans, and
        # steady-state signature churn fires a tracer instant + flight
        # dump.  Pass an instance or True (defaults); None keeps the
        # pre-PR-12 behavior exactly.
        from deepspeed_tpu.tracing import CompileWatchdog
        if isinstance(compile_watchdog, CompileWatchdog):
            self.compile_watchdog = compile_watchdog.bind(
                tracer=self.tracer if compile_watchdog.tracer
                is NULL_TRACER else None,
                flight_recorder=self.flight_recorder)
        elif compile_watchdog:
            self.compile_watchdog = CompileWatchdog(
                tracer=self.tracer,
                flight_recorder=self.flight_recorder)
        else:
            self.compile_watchdog = None
        self.stall_timeout_s = stall_timeout_s
        self.straggler_factor = float(straggler_factor)
        self.gauge_interval = int(gauge_interval)
        self.mfu_gauge = bool(mfu_gauge)
        self._peak_flops_per_device = peak_flops_per_device
        self._peak_flops_total = None
        self._flops = None              # lazy flops_profile (False = n/a)
        self._ema_step_s = None
        self._ema_n = 0
        self._last_mfu = None
        self._last_tokens_per_s = None
        self._progress_beat = time.monotonic()
        self._stall_fired = False
        self._watchdog_paused = False
        self._watchdog = None
        self._gauge_t0 = time.monotonic()
        self._gauge_steps0 = 0

    # ------------------------------------------------------------- events
    def _emit_events(self, events):
        """The unified monitor funnel: ring buffer + extra sink + the
        engine's monitor.  Steps are clamped to >= 1 locally (the
        pre-first-step gauges legitimately predate step 1; sinks index
        by positive step — same invariant monitor.clamp_min_step owns
        for MonitorMaster)."""
        events = [(tag, float(value), max(1, int(step)))
                  for tag, value, step in events]
        self.ring.write_events(events)
        if self._extra_monitor is not None:
            self._extra_monitor.write_events(events)
        eng_mon = getattr(self.engine, "monitor", None)
        if eng_mon is not None and getattr(eng_mon, "enabled", False):
            eng_mon.write_events(events)

    def _emit(self, tag, value):
        self._emit_events([(f"resilience/{tag}", float(value),
                            self.engine.global_steps)])

    def status(self):
        """Live snapshot for operators/tests."""
        return {
            "global_steps": self.engine.global_steps,
            "preempt_requested": self._preempt_requested,
            "report": dataclasses.asdict(self.report),
            "tags": self._tags(),
            "latest": self._read_latest(),
            "recent_events": self.ring.tail(20),
            "run_id": self.run_id,
            "incarnation": self.incarnation,
            "goodput": self.ledger.as_dict(),
        }

    def prometheus_text(self, prefix="ds_train"):
        """The training-side Prometheus exposition: goodput seconds +
        fractions, throughput/MFU gauges and run counters as
        ``<prefix>_*`` gauges (the serving twin is
        ``prometheus_text(sched.health())``)."""
        led = self.ledger.as_dict()
        flat = {"wall_s": led["wall_s"],
                "global_steps": self.engine.global_steps,
                "incarnation": self.incarnation,
                "steps": self.report.steps,
                "saves": self.report.saves,
                "save_retries": self.report.save_retries,
                "restores": self.report.restores,
                "nan_events": self.report.nan_events,
                "stragglers": self.report.stragglers,
                "stalls": self.report.stalls,
                "mfu": self._last_mfu,
                "tokens_per_s": self._last_tokens_per_s,
                "ema_step_s": self._ema_step_s}
        for cat in CATEGORIES:
            flat[f"goodput_{cat}_s"] = led["seconds"][cat]
            flat[f"goodput_{cat}_frac"] = led["fractions"][cat]
        flat = {k: v for k, v in flat.items() if v is not None}
        return prometheus_text(flat, prefix=prefix,
                               labels={"run_id": self.run_id})

    # --------------------------------------------------------- run state
    def _read_run_state(self):
        try:
            with open(self._run_state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _write_run_state(self):
        try:
            os.makedirs(self.save_dir, exist_ok=True)
            st = {"run_id": self.run_id,
                  "incarnations": self.incarnation,
                  "max_step_reached": self._max_step_reached,
                  "ledger": self.ledger.snapshot(),
                  "wall_time": time.time()}
            tmp = self._run_state_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(st, f)
            os.replace(tmp, self._run_state_path)
        except OSError as e:
            logger.warning(f"run_state write failed: {e}")

    def _flush_trace(self, merge=True):
        """Drain the span ring into this incarnation's file (appended —
        per-incarnation files stay disjoint) and, with ``merge``,
        rebuild the merged run trace.  Called at every verified save
        (``merge=False`` — re-merging all history per save would make
        checkpoint I/O grow with run length; ``merge_train_trace`` is a
        public entry point for post-mortems on a SIGKILLed run) and at
        train() exit (clean, preempted or crashed-with-exception,
        ``merge=True``); a SIGKILL loses only spans since the last
        flush — the same at-least-once window as the serving workers'
        heartbeat flushes."""
        if not self.tracer.enabled or not self.trace_dir:
            return
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            # high-water mark, NOT drain: the ring must keep its window
            # so a flight dump right after a save still shows recent
            # history.  Total-pushed = dropped + len(ring); the last
            # (total - flushed) ring entries are the unflushed ones.
            events = self.tracer.serialized()
            pushed_total = self.tracer.dropped + len(events)
            new = pushed_total - self._trace_flushed_total
            if new > len(events):
                logger.warning(
                    f"{new - len(events)} spans rotated out of the ring "
                    "before reaching disk (raise SpanTracer capacity or "
                    "save more often)")
                new = len(events)
            events = events[len(events) - new:] if new > 0 else []
            self._trace_flushed_total = pushed_total
            if events:
                # append-only JSONL: one event per line, so a flush
                # costs O(new spans) regardless of how long the run has
                # been going (and a torn tail line after SIGKILL drops
                # one event, not the file)
                path = os.path.join(
                    self.trace_dir,
                    f"spans_inc{max(1, self.incarnation):03d}.jsonl")
                with open(path, "a") as f:
                    for ev in events:
                        f.write(json.dumps(ev))
                        f.write("\n")
            if merge and os.path.isdir(self.trace_dir):
                merge_train_trace(self.trace_dir)
        except OSError as e:
            logger.warning(f"trace flush failed: {e}")

    # ---------------------------------------------------------- signals
    def request_preemption(self):
        """Programmatic preemption notice (same path as SIGTERM)."""
        self._preempt_requested = True

    def _on_signal(self, signum, frame):
        # NEVER save here: the signal may land mid-step with optimizer
        # buffers donated to XLA. Set the flag; the loop finishes the
        # in-flight step, then saves at a step boundary.
        self._preempt_requested = True
        logger.warning(f"received signal {signum}: will checkpoint and "
                       "exit at the next step boundary")

    def _install_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return      # signal.signal is main-thread-only
        for sig in self.signals:
            self._old_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore_signals(self):
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers = {}

    # ------------------------------------------------------- checkpoints
    def _tag_step(self, tag):
        m = re.search(r"(\d+)$", tag)
        return int(m.group(1)) if m else -1

    def _tags(self):
        """Existing (non-quarantined) tags, oldest -> newest by the step
        number embedded in the tag name."""
        if not os.path.isdir(self.save_dir):
            return []
        out = []
        for name in os.listdir(self.save_dir):
            full = os.path.join(self.save_dir, name)
            if not os.path.isdir(full) or name.endswith(".corrupt"):
                continue
            if os.path.exists(os.path.join(full, "checkpoint_meta.json")) \
                    or os.path.exists(os.path.join(full,
                                                   "model_states.npz")):
                out.append(name)
        return sorted(out, key=self._tag_step)

    def _read_latest(self):
        f = os.path.join(self.save_dir, "latest")
        if not os.path.exists(f):
            return None
        with open(f) as fh:
            return fh.read().strip()

    def _advance_latest(self, tag):
        tmp = os.path.join(self.save_dir, "latest.tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
        os.replace(tmp, os.path.join(self.save_dir, "latest"))

    def _rotate(self):
        tags = self._tags()
        latest = self._read_latest()
        for tag in tags[:-self.keep_last] if self.keep_last > 0 else []:
            if tag == latest:
                continue
            full = os.path.join(self.save_dir, tag)
            try:
                import shutil
                shutil.rmtree(full)
                self._emit("checkpoint_rotated", self._tag_step(tag))
            except OSError as e:
                logger.warning(f"rotation of {full} failed: {e}")

    def _quarantine(self, tag):
        full = os.path.join(self.save_dir, tag)
        try:
            os.replace(full, full + ".corrupt")
            logger.warning(f"quarantined corrupt checkpoint {full}")
        except OSError as e:
            logger.warning(f"could not quarantine {full}: {e}")

    def _rng_state(self):
        key = getattr(self.engine, "_rng", None)
        if key is None:
            return None
        try:
            data = jax.random.key_data(key)
        except Exception:
            data = key
        return np.asarray(jax.device_get(data)).astype(np.uint32).tolist()

    def _restore_rng(self, client):
        saved = (client.get("resilience") or {}).get("rng_key")
        if saved is None:
            return
        try:
            self.engine._rng = jnp.asarray(saved, jnp.uint32)
        except Exception as e:     # typed-key runtimes: best effort
            logger.warning(f"rng restore skipped: {e}")

    def save(self, tag=None, budget_s=None):
        """Checkpoint with bounded retry-with-backoff; the ``latest``
        pointer advances only after the on-disk files pass
        ``verify_checkpoint``. ``budget_s`` bounds the whole retry loop
        in wall time (the preemption path passes the SIGTERM grace
        window — better to surface the error while the process can
        still log it than to sleep into SIGKILL). Returns the tag
        path."""
        tag = str(tag or f"{self.tag_prefix}{self.engine.global_steps}")
        path = os.path.join(self.save_dir, tag)
        t_save0 = time.monotonic()
        self._watchdog_paused = True
        try:
            deadline = None if budget_s is None \
                else time.monotonic() + budget_s
            last_err = None
            for attempt in range(1, self.save_retries + 1):
                try:
                    client = {"resilience": {
                        "rng_key": self._rng_state(),
                        # trace/ledger continuity survives even if
                        # run_state.json is lost with the work dir
                        "run_id": self.run_id,
                        "max_step_reached": self._max_step_reached}}
                    # synchronous by design: the integrity gate below
                    # must read the durable bytes before `latest` may
                    # advance, so an async writer would be joined
                    # immediately anyway (the engine's own async_save
                    # remains available for unsupervised checkpointing)
                    with tracing.scope(self.tracer):
                        self.engine.save_checkpoint(
                            self.save_dir, tag=tag, client_state=client,
                            save_latest=False, async_save=False)
                        self.engine.wait_checkpoint()
                    with self.tracer.span("ckpt_verify", cat="ckpt",
                                          track="ckpt",
                                          args={"tag": tag}):
                        ok, problems = verify_checkpoint(path)
                    if not ok:
                        raise CheckpointCorrupt(
                            f"post-save verification of {path} failed: "
                            + "; ".join(problems))
                    self._advance_latest(tag)
                    with self.tracer.span("rotate", cat="ckpt",
                                          track="ckpt"):
                        self._rotate()
                    self.report.saves += 1
                    self._emit("checkpoint_saved", self.engine.global_steps)
                    self._write_run_state()
                    self._flush_trace(merge=False)
                    return path
                except Exception as e:
                    last_err = e
                    self.report.save_retries += 1
                    self._emit("save_retry", attempt)
                    logger.warning(
                        f"checkpoint save attempt {attempt}/"
                        f"{self.save_retries} failed: {e}")
                    backoff = self.retry_backoff_s * (2 ** (attempt - 1))
                    if deadline is not None and \
                            time.monotonic() + backoff >= deadline:
                        logger.error(
                            "save budget exhausted before the grace window "
                            "ends; giving up rather than sleeping into "
                            "SIGKILL")
                        break
                    if attempt < self.save_retries:
                        time.sleep(backoff)
            raise last_err
        finally:
            t_save1 = time.monotonic()
            # beat reset BEFORE unpausing: the watchdog polling between
            # the two writes must never see unpaused + a pre-save beat
            self._progress_beat = time.monotonic()
            self._watchdog_paused = False
            if self.ledger.active:
                self.ledger.add("checkpoint_stall", t_save1 - t_save0)
            self.tracer.complete("ckpt_save", t_save0, t_save1,
                                 cat="ckpt", track="ckpt",
                                 args={"tag": tag})

    def resume(self, example_batch=None):
        """Restore the newest INTACT tag (rollback order: descending
        step number; every candidate is verified before any restore is
        attempted — never a silent partial restore). Returns the tag
        loaded, or None when no intact checkpoint exists."""
        t0 = time.monotonic()
        restored = None
        self._watchdog_paused = True
        try:
            for tag in reversed(self._tags()):
                path = os.path.join(self.save_dir, tag)
                ok, problems = verify_checkpoint(path)
                if not ok:
                    logger.warning(
                        f"checkpoint {path} failed verification "
                        f"({'; '.join(problems[:3])}); rolling back")
                    self._emit("rollback", self._tag_step(tag))
                    self.tracer.instant(
                        "rollback", cat="ckpt", track="ckpt",
                        args={"tag": tag, "reason": "verify_failed"})
                    if self.flight_recorder is not None:
                        self.flight_recorder.dump(
                            f"ckpt_rollback_{tag}",
                            extra={"tag": tag,
                                   "problems": problems[:5]})
                    self._quarantine(tag)
                    continue
                try:
                    _, client = self.engine.load_checkpoint(
                        self.save_dir, tag=tag,
                        example_batch=example_batch)
                except Exception as e:
                    # verified-but-unloadable (e.g. structure mismatch):
                    # surface it, try older — but do NOT quarantine; the
                    # files are intact
                    logger.warning(f"restore of {path} failed: {e}")
                    self._emit("rollback", self._tag_step(tag))
                    self.tracer.instant(
                        "rollback", cat="ckpt", track="ckpt",
                        args={"tag": tag, "reason": "load_failed"})
                    continue
                self._restore_rng(client or {})
                saved = (client or {}).get("resilience") or {}
                # run identity fallback: when run_state.json was lost
                # (checkpoints copied to a fresh save_dir, work-dir
                # cleanup) the checkpoint's own record keeps the run id
                # stable, so the merged trace and the ds_train_* run_id
                # label don't fork mid-run
                if not self._had_run_state and saved.get("run_id"):
                    self.run_id = str(saved["run_id"])
                    self._had_run_state = True
                # recompute attribution after the restore: the furthest
                # step the run EVER reached, from the checkpoint's own
                # record (run_state.json may be newer; take the max)
                if saved.get("max_step_reached"):
                    self._max_step_reached = max(
                        self._max_step_reached,
                        int(saved["max_step_reached"]))
                self._advance_latest(tag)   # repair a latest that pointed
                self.report.resumed_from = tag  # at a quarantined tag
                self._emit("resumed", self._tag_step(tag))
                restored = tag
                return tag
            return None
        finally:
            self._progress_beat = time.monotonic()
            self._watchdog_paused = False
            self.tracer.complete("resume", t0, time.monotonic(),
                                 cat="ckpt", track="ckpt",
                                 args={"restored": restored})

    # --------------------------------------------------- goodput + gauges
    def _compile_count(self):
        probe = getattr(self.engine, "train_compile_count", None)
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            return None

    def _account_step(self, fstep, t0, t1, pre_cc):
        """Classify one completed train step's wall time, feed the
        EWMA straggler watchdog, and advance the progress beat."""
        dt = t1 - t0
        post_cc = self._compile_count()
        if pre_cc is not None and post_cc is not None and post_cc > pre_cc:
            category = "compile_warmup"
            if self.compile_watchdog is not None:
                self.compile_watchdog.on_compile(
                    "train_step", post_cc - pre_cc, t0, t1,
                    detail={"step": fstep})
        elif fstep < self._max_step_reached:
            category = "recompute"
        else:
            category = "productive"
        self.ledger.add(category, dt)
        if self.compile_watchdog is not None and \
                category != "compile_warmup":
            self.compile_watchdog.step()   # auto-steady quiet ticker
        self.tracer.complete("train_step", t0, t1, cat="train",
                             track="steps",
                             args={"step": fstep, "category": category,
                                   "ms": round(dt * 1e3, 3)})
        # EWMA straggler watchdog — compile steps are expected outliers
        # and stay out of both the check and the average
        if category != "compile_warmup":
            if self._ema_step_s is not None and self._ema_n >= 3 and \
                    dt > self.straggler_factor * self._ema_step_s:
                self.report.stragglers += 1
                self._emit_events([("train/straggler", dt, fstep + 1)])
                self.tracer.instant(
                    "straggler", cat="train", track="steps",
                    args={"step": fstep, "s": round(dt, 4),
                          "ema_s": round(self._ema_step_s, 4)})
                if self.flight_recorder is not None:
                    self.flight_recorder.dump(
                        f"train_straggler_step{fstep}",
                        extra={"step": fstep, "step_s": dt,
                               "ema_s": self._ema_step_s})
            self._ema_step_s = dt if self._ema_step_s is None \
                else 0.3 * dt + 0.7 * self._ema_step_s
            self._ema_n += 1
        self._max_step_reached = max(self._max_step_reached,
                                     self.engine.global_steps)
        self._progress_beat = time.monotonic()
        self._stall_fired = False
        self._write_run_state()
        return category

    def _flops_profile_cached(self):
        if self._flops is None:
            if not self.mfu_gauge:
                self._flops = False
            else:
                try:
                    self._flops = self.engine.flops_profile()
                except Exception as e:
                    logger.warning(
                        f"flops profile unavailable; MFU gauge off ({e})")
                    self._flops = False
        return self._flops or None

    def _resolve_peak(self):
        if self._peak_flops_total is None:
            per_dev = self._peak_flops_per_device
            if per_dev is None:
                from deepspeed_tpu.profiling.flops_profiler.profiler \
                    import peak_flops_per_device
                per_dev = peak_flops_per_device()
            self._peak_flops_total = float(per_dev) * jax.device_count()
        return self._peak_flops_total

    def _emit_gauges(self):
        """Per-window throughput gauges over WALL time since the last
        emission (bench semantics: data loading and bookkeeping count
        against throughput, exactly as they do in a real run)."""
        now = time.monotonic()
        steps = self.report.steps - self._gauge_steps0
        wall = now - self._gauge_t0
        if steps <= 0 or wall <= 0:
            return
        step_no = self.engine.global_steps
        events = [("train/step_time_ms", wall / steps * 1e3, step_no)]
        # the first call may pay a one-time XLA cost-analysis; it runs
        # AFTER this window's wall was read and BEFORE the next window
        # opens (below), so it lands in ledger idle, never in a gauge
        prof = self._flops_profile_cached()
        if prof:
            tokens_per_step = prof["flops_per_step"] / \
                max(prof["flops_per_token"], 1e-9)
            self._last_tokens_per_s = tokens_per_step * steps / wall
            achieved = prof["flops_per_step"] * steps / wall
            self._last_mfu = achieved / self._resolve_peak()
            events += [
                ("train/tokens_per_s", self._last_tokens_per_s, step_no),
                ("train/tflops_achieved", achieved / 1e12, step_no),
                ("train/mfu", self._last_mfu, step_no)]
        events += [(f"train/goodput/{c}", f, step_no)
                   for c, f in self.ledger.fractions().items()]
        self._emit_events(events)
        self._gauge_t0, self._gauge_steps0 = (time.monotonic(),
                                              self.report.steps)

    # ---------------------------------------------------------- training
    def train(self, num_steps, batch_fn=None, data_iter=None):
        """Run supervised training until ``engine.global_steps`` reaches
        ``num_steps`` (absolute, so a resumed run continues seamlessly),
        a preemption notice arrives, or the watchdog halts the run.

        ``batch_fn(global_step)`` returns the micro-batch (or list of
        gas micro-batches) for that step — keying data on the persisted
        step counter is what makes an interrupted+resumed run replay the
        exact byte stream of an uninterrupted one.
        """
        assert batch_fn is not None or data_iter is not None or \
            self.engine.training_dataloader is not None
        self.report = TrainReport()
        self.incarnation += 1
        self.report.run_id = self.run_id
        self.report.incarnation = self.incarnation
        if self.tracer.enabled:
            self.tracer.process = \
                f"train:{self.run_id}:inc{self.incarnation}"
        consecutive_nan = 0
        self._install_signals()
        self.ledger.begin()
        self._gauge_t0 = time.monotonic()
        self._gauge_steps0 = 0
        self._progress_beat = time.monotonic()
        self._stall_fired = False
        self._write_run_state()
        if self.stall_timeout_s:
            self._watchdog = _NoProgressWatchdog(self)
            self._watchdog.start()
        try:
            while self.engine.global_steps < num_steps:
                if self._preempt_requested:
                    t_drain = time.monotonic()
                    step = self.engine.global_steps
                    self.report.preempted_at_step = step
                    self.tracer.instant("preemption", cat="train",
                                        track="steps",
                                        args={"step": step})
                    if self.flight_recorder is not None:
                        self.flight_recorder.dump(
                            f"preemption_step{step}",
                            extra={"step": step})
                    tag = f"{self.tag_prefix}{step}"
                    if self._read_latest() != tag:   # periodic save may
                        self.save(tag,               # have just landed
                                  budget_s=self.preemption_grace_s)
                    self.report.status = "preempted"
                    self._emit("preempted", step)
                    self.tracer.complete("preemption_drain", t_drain,
                                         time.monotonic(), cat="train",
                                         track="steps",
                                         args={"step": step})
                    logger.warning(
                        f"preemption checkpoint at step {step}; "
                        "exiting cleanly")
                    return self.report
                batches = None
                if batch_fn is not None:
                    with self.tracer.span(
                            "data_load", cat="train", track="data",
                            args={"step": self.engine.global_steps}):
                        batches = batch_fn(self.engine.global_steps)
                    if isinstance(batches, dict):
                        batches = [batches]
                fstep = self.engine.global_steps
                pre_cc = self._compile_count()
                t0 = time.monotonic()
                loss = self.engine.train_batch(data_iter=data_iter,
                                               batches=batches, sync=True)
                self._account_step(fstep, t0, time.monotonic(), pre_cc)
                self.report.steps += 1
                self.report.last_loss = float(loss)
                if not np.isfinite(loss):
                    consecutive_nan += 1
                    self.report.nan_events += 1
                    self._emit("nan_loss", self.engine.global_steps)
                    self._handle_nan(consecutive_nan)
                else:
                    consecutive_nan = 0
                if self.save_interval and self.engine.global_steps and \
                        self.engine.global_steps % self.save_interval == 0:
                    self.save()
                if self.gauge_interval and \
                        self.report.steps % self.gauge_interval == 0:
                    self._emit_gauges()
            self.report.status = "completed"
            return self.report
        finally:
            self._restore_signals()
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
            self.ledger.finish()
            self.report.ledger = self.ledger.as_dict()
            self.report.mfu = self._last_mfu
            self.report.tokens_per_s = self._last_tokens_per_s
            step_no = self.engine.global_steps
            self._emit_events(
                [(f"train/goodput/{c}", f, step_no)
                 for c, f in self.ledger.fractions().items()])
            self._write_run_state()
            self._flush_trace()

    def _handle_nan(self, consecutive_nan):
        t0 = time.monotonic()
        try:
            if self.flight_recorder is not None:
                self.flight_recorder.dump(
                    f"divergence_step{self.engine.global_steps}",
                    extra={"step": self.engine.global_steps,
                           "policy": self.nan_policy})
            if self.nan_policy == "halt":
                raise DivergenceError(
                    f"non-finite loss at step {self.engine.global_steps}")
            if self.nan_policy == "skip":
                logger.warning(
                    f"non-finite loss at step {self.engine.global_steps}; "
                    f"policy=skip ({consecutive_nan} consecutive)")
                if consecutive_nan > self.max_nan_events:
                    raise DivergenceError(
                        f"{consecutive_nan} consecutive non-finite losses "
                        f"exceed budget {self.max_nan_events}")
                return
            # restore policy: roll back to the newest intact checkpoint
            if self.report.restores >= self.max_nan_events:
                raise DivergenceError(
                    f"watchdog restore budget ({self.max_nan_events}) "
                    "exhausted")
            tag = self.resume()
            if tag is None:
                raise DivergenceError(
                    "non-finite loss and no intact checkpoint to restore")
            self.report.restores += 1
            logger.warning(
                f"non-finite loss: restored {tag} "
                f"(step {self.engine.global_steps}) and continuing")
        finally:
            if self.ledger.active:
                self.ledger.add("divergence_retry",
                                time.monotonic() - t0)
