"""Training supervision: a self-healing wrapper around DeepSpeedEngine.

``ResilientTrainer`` owns the failure modes a long preemptible-capacity
run actually dies from (Bamboo, NSDI '23; the reference's elastic
training + Nebula tiered checkpoints):

* **Preemption** — SIGTERM sets a flag, the in-flight step finishes,
  a checkpoint is saved, and ``train()`` returns cleanly with status
  ``"preempted"`` (the contract ``elasticity/elastic_agent.py``'s
  graceful ``terminate()`` relies on).
* **Periodic checkpointing** with retention/rotation, where the
  ``latest`` pointer only advances after
  :func:`~deepspeed_tpu.checkpoint.engine.verify_checkpoint` passes —
  a crash can leave a torn tag on disk but never a ``latest`` that
  points at one.
* **Rollback** — ``resume()`` walks tags newest-first, verifying each,
  and restores the newest *intact* one; corrupt tags are quarantined
  (renamed ``<tag>.corrupt``) so they are never retried. A restore is
  all-or-nothing: the engine's state is only replaced after the full
  tree loads, so a corrupt shard can never leave a partial mix.
* **Transient save failures** — bounded retry with exponential backoff
  (each attempt is a fresh ``save_id``, so a half-written attempt can
  never contaminate the retry).
* **NaN/divergence watchdog** — a non-finite loss is skipped-and-logged
  or rolled back to the last good checkpoint, per policy, with a
  bounded budget before the run halts loudly.

All events flow through ``monitor/`` (``resilience/*`` tags) and are
kept in an in-memory :class:`~deepspeed_tpu.monitor.monitor.RingBufferMonitor`
for ``status()`` introspection.

Every recovery path here is covered by the deterministic fault harness
(:mod:`deepspeed_tpu.resilience.faults`) in
``tests/unit/test_resilience.py``.
"""

import dataclasses
import os
import re
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.checkpoint.engine import (CheckpointCorrupt,
                                             verify_checkpoint)
from deepspeed_tpu.monitor.monitor import RingBufferMonitor
from deepspeed_tpu.utils.logging import logger


class Preempted(RuntimeError):
    """A preemption notice (SIGTERM) interrupted training; state was
    checkpointed and the process should exit cleanly."""


class DivergenceError(RuntimeError):
    """The NaN/divergence watchdog exhausted its recovery budget."""


@dataclasses.dataclass
class TrainReport:
    """What happened during one supervised ``train()`` call."""
    status: str = "completed"       # completed | preempted
    steps: int = 0                  # train_batch calls that ran
    last_loss: float = float("nan")
    nan_events: int = 0
    restores: int = 0               # watchdog rollbacks
    saves: int = 0                  # checkpoints that passed verification
    save_retries: int = 0           # failed save attempts that were retried
    resumed_from: str = None        # tag resume() restored, if any
    preempted_at_step: int = None


class ResilientTrainer:
    """Supervised training loop over a ``DeepSpeedEngine``.

    Args:
        engine: a live ``DeepSpeedEngine``.
        save_dir: checkpoint root (tags are subdirectories).
        save_interval: save every N optimizer steps (0 = only on
            preemption / explicit ``save()``).
        keep_last: retention — newest N verified tags are kept, older
            ones rotate out (the tag ``latest`` points to is never
            removed).
        save_retries: attempts per save before giving up.
        retry_backoff_s: base backoff; doubles per failed attempt.
        nan_policy: ``"restore"`` (roll back to last good checkpoint),
            ``"skip"`` (log and continue), or ``"halt"``.
        max_nan_events: recovery budget — restores (restore policy) or
            consecutive NaN steps (skip policy) beyond this raise
            :class:`DivergenceError`.
        monitor: optional extra ``write_events`` sink; the engine's own
            monitor (when enabled) and the internal ring buffer always
            receive events.
        signals: signals treated as preemption notices during
            ``train()`` (default: SIGTERM).
        preemption_grace_s: wall-time budget for the preemption save
            (the SIGTERM-to-SIGKILL window). Defaults to the
            ``DS_PREEMPTION_GRACE_S`` env var the elastic agent
            publishes; None means unbounded.
    """

    def __init__(self, engine, save_dir, *, save_interval=0, keep_last=3,
                 tag_prefix="step", save_retries=3, retry_backoff_s=0.25,
                 nan_policy="restore", max_nan_events=3,
                 monitor=None, signals=(signal.SIGTERM,),
                 preemption_grace_s=None):
        if nan_policy not in ("restore", "skip", "halt"):
            raise ValueError(f"unknown nan_policy {nan_policy!r}")
        self.engine = engine
        self.save_dir = str(save_dir)
        self.save_interval = int(save_interval)
        self.keep_last = int(keep_last)
        self.tag_prefix = tag_prefix
        self.save_retries = int(save_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # SIGTERM-to-SIGKILL window (elastic_agent's term_grace_s, which
        # it publishes as DS_PREEMPTION_GRACE_S): the preemption save
        # must not retry-and-backoff past the point where the agent
        # escalates to SIGKILL and tears the write mid-file anyway
        if preemption_grace_s is None:
            env = os.environ.get("DS_PREEMPTION_GRACE_S")
            preemption_grace_s = float(env) if env else None
        self.preemption_grace_s = preemption_grace_s
        self.nan_policy = nan_policy
        self.max_nan_events = int(max_nan_events)
        self.ring = RingBufferMonitor()
        self._extra_monitor = monitor
        self.signals = tuple(signals)
        self._preempt_requested = False
        self._old_handlers = {}
        self.report = TrainReport()

    # ------------------------------------------------------------- events
    def _emit(self, tag, value):
        events = [(f"resilience/{tag}", float(value),
                   self.engine.global_steps)]
        self.ring.write_events(events)
        if self._extra_monitor is not None:
            self._extra_monitor.write_events(events)
        eng_mon = getattr(self.engine, "monitor", None)
        if eng_mon is not None and getattr(eng_mon, "enabled", False):
            eng_mon.write_events(events)

    def status(self):
        """Live snapshot for operators/tests."""
        return {
            "global_steps": self.engine.global_steps,
            "preempt_requested": self._preempt_requested,
            "report": dataclasses.asdict(self.report),
            "tags": self._tags(),
            "latest": self._read_latest(),
            "recent_events": self.ring.tail(20),
        }

    # ---------------------------------------------------------- signals
    def request_preemption(self):
        """Programmatic preemption notice (same path as SIGTERM)."""
        self._preempt_requested = True

    def _on_signal(self, signum, frame):
        # NEVER save here: the signal may land mid-step with optimizer
        # buffers donated to XLA. Set the flag; the loop finishes the
        # in-flight step, then saves at a step boundary.
        self._preempt_requested = True
        logger.warning(f"received signal {signum}: will checkpoint and "
                       "exit at the next step boundary")

    def _install_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return      # signal.signal is main-thread-only
        for sig in self.signals:
            self._old_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore_signals(self):
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers = {}

    # ------------------------------------------------------- checkpoints
    def _tag_step(self, tag):
        m = re.search(r"(\d+)$", tag)
        return int(m.group(1)) if m else -1

    def _tags(self):
        """Existing (non-quarantined) tags, oldest -> newest by the step
        number embedded in the tag name."""
        if not os.path.isdir(self.save_dir):
            return []
        out = []
        for name in os.listdir(self.save_dir):
            full = os.path.join(self.save_dir, name)
            if not os.path.isdir(full) or name.endswith(".corrupt"):
                continue
            if os.path.exists(os.path.join(full, "checkpoint_meta.json")) \
                    or os.path.exists(os.path.join(full,
                                                   "model_states.npz")):
                out.append(name)
        return sorted(out, key=self._tag_step)

    def _read_latest(self):
        f = os.path.join(self.save_dir, "latest")
        if not os.path.exists(f):
            return None
        with open(f) as fh:
            return fh.read().strip()

    def _advance_latest(self, tag):
        tmp = os.path.join(self.save_dir, "latest.tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
        os.replace(tmp, os.path.join(self.save_dir, "latest"))

    def _rotate(self):
        tags = self._tags()
        latest = self._read_latest()
        for tag in tags[:-self.keep_last] if self.keep_last > 0 else []:
            if tag == latest:
                continue
            full = os.path.join(self.save_dir, tag)
            try:
                import shutil
                shutil.rmtree(full)
                self._emit("checkpoint_rotated", self._tag_step(tag))
            except OSError as e:
                logger.warning(f"rotation of {full} failed: {e}")

    def _quarantine(self, tag):
        full = os.path.join(self.save_dir, tag)
        try:
            os.replace(full, full + ".corrupt")
            logger.warning(f"quarantined corrupt checkpoint {full}")
        except OSError as e:
            logger.warning(f"could not quarantine {full}: {e}")

    def _rng_state(self):
        key = getattr(self.engine, "_rng", None)
        if key is None:
            return None
        try:
            data = jax.random.key_data(key)
        except Exception:
            data = key
        return np.asarray(jax.device_get(data)).astype(np.uint32).tolist()

    def _restore_rng(self, client):
        saved = (client.get("resilience") or {}).get("rng_key")
        if saved is None:
            return
        try:
            self.engine._rng = jnp.asarray(saved, jnp.uint32)
        except Exception as e:     # typed-key runtimes: best effort
            logger.warning(f"rng restore skipped: {e}")

    def save(self, tag=None, budget_s=None):
        """Checkpoint with bounded retry-with-backoff; the ``latest``
        pointer advances only after the on-disk files pass
        ``verify_checkpoint``. ``budget_s`` bounds the whole retry loop
        in wall time (the preemption path passes the SIGTERM grace
        window — better to surface the error while the process can
        still log it than to sleep into SIGKILL). Returns the tag
        path."""
        tag = str(tag or f"{self.tag_prefix}{self.engine.global_steps}")
        path = os.path.join(self.save_dir, tag)
        deadline = None if budget_s is None else time.monotonic() + budget_s
        last_err = None
        for attempt in range(1, self.save_retries + 1):
            try:
                client = {"resilience": {"rng_key": self._rng_state()}}
                # synchronous by design: the integrity gate below must
                # read the durable bytes before `latest` may advance, so
                # an async writer would be joined immediately anyway
                # (the engine's own async_save remains available for
                # unsupervised checkpointing)
                self.engine.save_checkpoint(
                    self.save_dir, tag=tag, client_state=client,
                    save_latest=False, async_save=False)
                self.engine.wait_checkpoint()
                ok, problems = verify_checkpoint(path)
                if not ok:
                    raise CheckpointCorrupt(
                        f"post-save verification of {path} failed: "
                        + "; ".join(problems))
                self._advance_latest(tag)
                self._rotate()
                self.report.saves += 1
                self._emit("checkpoint_saved", self.engine.global_steps)
                return path
            except Exception as e:
                last_err = e
                self.report.save_retries += 1
                self._emit("save_retry", attempt)
                logger.warning(
                    f"checkpoint save attempt {attempt}/"
                    f"{self.save_retries} failed: {e}")
                backoff = self.retry_backoff_s * (2 ** (attempt - 1))
                if deadline is not None and \
                        time.monotonic() + backoff >= deadline:
                    logger.error(
                        "save budget exhausted before the grace window "
                        "ends; giving up rather than sleeping into "
                        "SIGKILL")
                    break
                if attempt < self.save_retries:
                    time.sleep(backoff)
        raise last_err

    def resume(self, example_batch=None):
        """Restore the newest INTACT tag (rollback order: descending
        step number; every candidate is verified before any restore is
        attempted — never a silent partial restore). Returns the tag
        loaded, or None when no intact checkpoint exists."""
        for tag in reversed(self._tags()):
            path = os.path.join(self.save_dir, tag)
            ok, problems = verify_checkpoint(path)
            if not ok:
                logger.warning(
                    f"checkpoint {path} failed verification "
                    f"({'; '.join(problems[:3])}); rolling back")
                self._emit("rollback", self._tag_step(tag))
                self._quarantine(tag)
                continue
            try:
                _, client = self.engine.load_checkpoint(
                    self.save_dir, tag=tag, example_batch=example_batch)
            except Exception as e:
                # verified-but-unloadable (e.g. structure mismatch):
                # surface it, try older — but do NOT quarantine; the
                # files are intact
                logger.warning(f"restore of {path} failed: {e}")
                self._emit("rollback", self._tag_step(tag))
                continue
            self._restore_rng(client or {})
            self._advance_latest(tag)   # repair a latest that pointed
            self.report.resumed_from = tag  # at a now-quarantined tag
            self._emit("resumed", self._tag_step(tag))
            return tag
        return None

    # ---------------------------------------------------------- training
    def train(self, num_steps, batch_fn=None, data_iter=None):
        """Run supervised training until ``engine.global_steps`` reaches
        ``num_steps`` (absolute, so a resumed run continues seamlessly),
        a preemption notice arrives, or the watchdog halts the run.

        ``batch_fn(global_step)`` returns the micro-batch (or list of
        gas micro-batches) for that step — keying data on the persisted
        step counter is what makes an interrupted+resumed run replay the
        exact byte stream of an uninterrupted one.
        """
        assert batch_fn is not None or data_iter is not None or \
            self.engine.training_dataloader is not None
        self.report = TrainReport()
        consecutive_nan = 0
        self._install_signals()
        try:
            while self.engine.global_steps < num_steps:
                if self._preempt_requested:
                    self.report.preempted_at_step = self.engine.global_steps
                    tag = f"{self.tag_prefix}{self.engine.global_steps}"
                    if self._read_latest() != tag:   # periodic save may
                        self.save(tag,               # have just landed
                                  budget_s=self.preemption_grace_s)
                    self.report.status = "preempted"
                    self._emit("preempted", self.engine.global_steps)
                    logger.warning(
                        f"preemption checkpoint at step "
                        f"{self.engine.global_steps}; exiting cleanly")
                    return self.report
                batches = None
                if batch_fn is not None:
                    batches = batch_fn(self.engine.global_steps)
                    if isinstance(batches, dict):
                        batches = [batches]
                loss = self.engine.train_batch(data_iter=data_iter,
                                               batches=batches, sync=True)
                self.report.steps += 1
                self.report.last_loss = float(loss)
                if not np.isfinite(loss):
                    consecutive_nan += 1
                    self.report.nan_events += 1
                    self._emit("nan_loss", self.engine.global_steps)
                    self._handle_nan(consecutive_nan)
                else:
                    consecutive_nan = 0
                if self.save_interval and self.engine.global_steps and \
                        self.engine.global_steps % self.save_interval == 0:
                    self.save()
            self.report.status = "completed"
            return self.report
        finally:
            self._restore_signals()

    def _handle_nan(self, consecutive_nan):
        if self.nan_policy == "halt":
            raise DivergenceError(
                f"non-finite loss at step {self.engine.global_steps}")
        if self.nan_policy == "skip":
            logger.warning(
                f"non-finite loss at step {self.engine.global_steps}; "
                f"policy=skip ({consecutive_nan} consecutive)")
            if consecutive_nan > self.max_nan_events:
                raise DivergenceError(
                    f"{consecutive_nan} consecutive non-finite losses "
                    f"exceed budget {self.max_nan_events}")
            return
        # restore policy: roll back to the newest intact checkpoint
        if self.report.restores >= self.max_nan_events:
            raise DivergenceError(
                f"watchdog restore budget ({self.max_nan_events}) "
                "exhausted")
        tag = self.resume()
        if tag is None:
            raise DivergenceError(
                "non-finite loss and no intact checkpoint to restore")
        self.report.restores += 1
        logger.warning(
            f"non-finite loss: restored {tag} "
            f"(step {self.engine.global_steps}) and continuing")
