"""Deterministic, seed-driven fault injection.

Every recovery path in the resilience subsystem is testable because the
code it protects carries *named injection points* — one-line hooks that
are free no-ops until a :class:`FaultInjector` is installed.  A test (or
a chaos run) arms an injector with a plan and replays the exact same
failure sequence on every run: triggers are keyed on exact step numbers,
on the n-th firing of a point, or on a seeded RNG — never on wall time.

Injection points wired through the codebase:

====================  =======================================  ==========
point                 site                                     ctx keys
====================  =======================================  ==========
``ckpt.shard_write``  before a shard file's bytes are written  ``path``
                      (``checkpoint/engine.py``) — a raised
                      IOError simulates a transient disk
                      failure for save-retry paths
``ckpt.shard_written``after the shard file is durably renamed  ``path``
                      — a callable action can truncate or
                      corrupt the on-disk file to exercise
                      integrity checking and rollback
``train.step``        entry of ``DeepSpeedEngine.train_batch`` ``step``
                      — raise, sleep (slow step) or deliver
                      SIGTERM to self (preemption)
``train.loss``        transform of train_batch's returned      ``step``
                      loss — force NaN for watchdog tests
``serve.step``        entry of ``ServingScheduler.step``.      ``step``
                      Since the fused-decode change one step
                      is one decode HORIZON (up to
                      ``decode_horizon_steps`` tokens per
                      slot), not one token: step-keyed plans
                      written against per-token timing should
                      pin ``decode_horizon_steps=1``
``serve.request``     per-request, before a token is emitted   ``step``,
                      — containment: the error must fail one   ``rid``
                      request, not the loop. Fires once at the
                      prefill-boundary first token and then
                      per token during horizon HARVEST, so a
                      raised decode-phase error lands at the
                      horizon boundary
``serve.page_alloc``  inside ``_grow_or_evict`` (horizon page
                      pre-reservation + prefill growth) and    ``step``,
                      the chained-dispatch reservation — raise ``slot``,
                      :class:`PagePoolExhausted` to force a    ``rid``
                      page-exhaustion episode on an exact
                      step regardless of actual pool size
                      (during a chained dispatch it aborts
                      the chain to the barrier path instead
                      of shedding). With the radix prefix
                      cache enabled the episode first DRAINS
                      refcount-free cached pages — cached
                      pages are reclaimable capacity — and
                      only sheds a victim once the cache is
                      empty/pinned
``serve.spec_verify`` speculative-decode rounds, twice: per    ``step``,
                      request while drafts are collected       ``slot``,
                      (ctx carries slot+rid — a raised         ``rid``
                      exception degrades THAT request to       (per-req
                      normal decode, sticky for its            firing
                      lifetime) and once per round just        only)
                      before the fused verify dispatch (ctx
                      is step-only — a raised exception
                      degrades the whole round to the
                      normal fused-horizon path). Either
                      way every request completes
                      token-exact and the loop survives;
                      contained degrades count in
                      ``health()['spec_degraded']``
``cluster.replica_   entry of a cluster replica's step          ``step``,
kill``                (``serving/cluster/replica.py``, both     ``replica``
                      backings) — a raised exception IS a
                      replica crash: the scheduler (or, for
                      a :class:`ProcessReplica`, the worker
                      process via SIGKILL) is dropped with
                      every in-flight request, and the
                      router must complete them all on
                      survivors via journal replay
                      (``step`` here is the ROUTER pump
                      index, not a scheduler step)
``cluster.handoff``   per packet in the router's prefill->      ``step``,
                      decode KV dispatch                        ``rid``
                      (``serving/cluster/router.py``) — a
                      raised exception fails ONE handoff:
                      its pages return to the pool and the
                      request requeues for unified serving,
                      token-exact either way
``cluster.router_    entry of ``ClusterRouter.step``, before    ``step``
kill``                anything else runs — a raised exception
                      IS the ROUTER's death (nothing after
                      the raise executes, exactly like a
                      process crash between pumps).  Under a
                      :class:`RouterSupervisor` the standby
                      acquires the next lease epoch, replays
                      the journal WAL tail, fences the fleet
                      and resumes: every request completes
                      exactly-once, sampled streams bitwise
                      identical to a kill-free run.  A
                      ``sleep`` action instead models a
                      STALLED primary: the lease expires,
                      the standby takes over, and the woken
                      zombie's dispatches/tokens/WAL appends
                      are all fenced
====================  =======================================  ==========

Usage::

    inj = FaultInjector(seed=0)
    inj.on("ckpt.shard_write", nth=1, exc=IOError("disk wobble"))
    inj.on("train.loss", step=4, replace=float("nan"))
    inj.on("serve.request", match={"rid": 2}, exc=RuntimeError("boom"))
    with faults.injected(inj):
        ...  # run the workload; faults fire deterministically

This module imports only stdlib + numpy so any layer (checkpoint,
runtime, serving) can import it without cycles.
"""

import contextlib
import os
import signal
import threading
import time

import numpy as np

_active = None          # the installed injector (module-global, like a
_lock = threading.Lock()  # logging root); serving/train loops are host
                          # threads, so arming is lock-protected
_observers = []         # callbacks notified when an armed plan FIRES
                        # (observability hooks: the serving flight
                        # recorder dumps its recent-span window at the
                        # exact moment injected chaos lands)


class Injection:
    """One armed fault: a trigger predicate plus an action.

    Trigger (all supplied conditions must hold):
      * ``step``  — ctx step equals this exact value
      * ``steps`` — ctx step is in this collection
      * ``nth``   — this is the n-th firing of the point (1-based),
                    counted per injection
      * ``match`` — every (key, value) equals the firing ctx's
      * ``prob``  — seeded coin flip (drawn from the injector's RNG, so
                    the decision sequence is a pure function of the seed)

    Action (first non-None wins):
      * ``exc``     — exception instance or class to raise
      * ``action``  — callable(ctx) for side effects (truncate a file,
                      sleep, kill -TERM self, ...)
      * ``replace`` — value substituted at ``transform`` points (or a
                      callable(value, ctx) -> new value)

    ``times`` bounds how often the action runs (default 1 — one-shot, so
    a retry/rollback pass after the fault is clean by default).
    """

    def __init__(self, point, *, step=None, steps=None, nth=None,
                 match=None, prob=None, times=1, exc=None, action=None,
                 replace=None):
        if exc is None and action is None and replace is None:
            raise ValueError("injection needs an action: exc=, action= "
                             "or replace=")
        self.point = point
        self.step = step
        self.steps = set(steps) if steps is not None else None
        self.nth = nth
        self.match = dict(match or {})
        self.prob = prob
        self.times = times
        self.exc = exc
        self.action = action
        self.replace = replace
        self.seen = 0       # firings of the point observed by this plan
        self.fired = 0      # times the action actually ran

    def _triggers(self, ctx, rng):
        self.seen += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.step is not None and ctx.get("step") != self.step:
            return False
        if self.steps is not None and ctx.get("step") not in self.steps:
            return False
        if self.nth is not None and self.seen != self.nth:
            return False
        for k, v in self.match.items():
            if ctx.get(k) != v:
                return False
        if self.prob is not None and not (rng.random() < self.prob):
            return False
        return True


class FaultInjector:
    """Replayable fault schedule over the named injection points."""

    def __init__(self, seed=0):
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self.plans = []
        self.log = []      # (point, step, ctx) for every action that ran

    def on(self, point, **kwargs):
        """Arm an injection (see :class:`Injection`); returns it so the
        caller can assert on ``.fired`` afterwards."""
        plan = Injection(point, **kwargs)
        self.plans.append(plan)
        return plan

    # --------------------------------------------------------- firing
    def _record(self, plan, ctx):
        plan.fired += 1
        self.log.append((plan.point, ctx.get("step"), dict(ctx)))
        for cb in list(_observers):
            try:
                cb(plan.point, dict(ctx))
            except Exception:
                pass    # an observer must never alter fault semantics

    def fire(self, point, **ctx):
        """Called from an instrumented site; raises or side-effects when
        an armed plan triggers."""
        for plan in self.plans:
            if plan.point != point or not plan._triggers(ctx, self.rng):
                continue
            self._record(plan, ctx)
            if plan.action is not None:
                plan.action(ctx)
            if plan.exc is not None:
                raise plan.exc if isinstance(plan.exc, BaseException) \
                    else plan.exc()

    def transform(self, point, value, **ctx):
        """Value-substitution variant for sites that return data (e.g.
        the train loss)."""
        for plan in self.plans:
            if plan.point != point or not plan._triggers(ctx, self.rng):
                continue
            self._record(plan, ctx)
            if plan.action is not None:
                plan.action(ctx)
            if plan.exc is not None:
                raise plan.exc if isinstance(plan.exc, BaseException) \
                    else plan.exc()
            if callable(plan.replace):
                value = plan.replace(value, ctx)
            elif plan.replace is not None:
                value = plan.replace
        return value


# ------------------------------------------------------------ site API
# The hooks instrumented code calls. They must cost one global load and
# one comparison when no injector is installed (the production path).

def fire(point, **ctx):
    inj = _active
    if inj is not None:
        inj.fire(point, **ctx)


def transform(point, value, **ctx):
    inj = _active
    if inj is None:
        return value
    return inj.transform(point, value, **ctx)


def install(injector):
    global _active
    with _lock:
        _active = injector
    return injector


def uninstall():
    global _active
    with _lock:
        _active = None


def get_injector():
    return _active


def observe(callback):
    """Register ``callback(point, ctx)`` to run whenever an armed plan's
    action fires (AFTER the action is recorded, BEFORE any exception
    propagates).  Observer errors are swallowed: observability must
    never change fault semantics.  Returns the callback for
    :func:`unobserve`."""
    _observers.append(callback)
    return callback


def unobserve(callback):
    try:
        _observers.remove(callback)
    except ValueError:
        pass


@contextlib.contextmanager
def injected(injector):
    """Scope an injector's lifetime; always uninstalls, so a failed test
    cannot leak faults into the next."""
    install(injector)
    try:
        yield injector
    finally:
        uninstall()


# ------------------------------------------------- stock fault actions

def truncate_file(nbytes=64):
    """Action: chop the last ``nbytes`` off ctx['path'] — a partial
    write surviving a crash (torn shard file)."""
    def act(ctx):
        path = ctx["path"]
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(0, size - nbytes))
    return act


def corrupt_file(offset=None, nbytes=8):
    """Action: overwrite ``nbytes`` at ``offset`` (default: mid-file)
    with complemented bits — silent on-media corruption the zip/CRC
    layers must catch."""
    def act(ctx):
        path = ctx["path"]
        size = os.path.getsize(path)
        off = size // 2 if offset is None else offset
        with open(path, "r+b") as f:
            f.seek(off)
            data = f.read(nbytes)
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in data))
    return act


def sleep_s(seconds):
    """Action: a slow step / slow write."""
    def act(ctx):
        time.sleep(seconds)
    return act


def sigterm_self():
    """Action: deliver SIGTERM to this process — a preemption notice,
    exactly what a cloud scheduler sends before reclaiming capacity."""
    def act(ctx):
        os.kill(os.getpid(), signal.SIGTERM)
    return act
