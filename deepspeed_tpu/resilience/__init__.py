"""Fault-tolerant run supervision.

Three coordinated parts (see docs/resilience.md):

* :mod:`deepspeed_tpu.resilience.faults` — deterministic, seed-driven
  fault injection through named points threaded into checkpoint writes,
  train steps and serving steps.
* :mod:`deepspeed_tpu.resilience.supervisor` —
  :class:`~deepspeed_tpu.resilience.supervisor.ResilientTrainer`:
  periodic + SIGTERM-triggered (preemption-safe) checkpointing,
  integrity-gated ``latest`` advancement, rollback to the newest intact
  tag, bounded save retries, and a NaN/divergence watchdog.
* Serving hardening lives in :mod:`deepspeed_tpu.serving` itself
  (deadlines, cancellation, per-request error containment, health).

``faults`` is imported eagerly (stdlib + numpy only, safe from any
layer); the supervisor — which pulls in the full runtime engine — loads
lazily so instrumented low-level modules can import this package
without cycles.
"""

from deepspeed_tpu.resilience import faults  # noqa: F401
from deepspeed_tpu.resilience.ledger import (CATEGORIES,  # noqa: F401
                                             GoodputLedger)

_LAZY = ("ResilientTrainer", "Preempted", "TrainReport", "DivergenceError",
         "merge_train_trace")


def __getattr__(name):
    if name in _LAZY:
        from deepspeed_tpu.resilience import supervisor
        return getattr(supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
