"""Decoding-policy subsystem: per-slot on-device logit pipeline,
lossless speculative sampling, grammar-constrained generation.

Three pillars (see each module's docstring for the contracts):

* :mod:`params`   — per-request :class:`SamplingParams` + the staged
  per-slot no-op encodings that keep mixed batches on ONE compiled
  signature per horizon/K bucket.
* :mod:`pipeline` — the traced processor chain (grammar mask ->
  penalties -> temperature -> top-k -> top-p -> sample) and the
  leftover-probability rejection-sampling kernel for lossless spec
  verification.
* :mod:`grammar`  — host-compiled regex / JSON-schema -> char DFA ->
  per-state token bitmask, with replayable per-request cursors.
"""

from .grammar import (CharDFA, GrammarConstraint, GrammarConstraintError,
                      RegexError, TokenDFA, byte_vocab, compile_grammar,
                      json_schema_to_regex, json_value_regex)
from .params import GREEDY, SamplingParams, request_key
from .pipeline import (accept_or_resample, bonus_sample, fold_keys,
                       process_logits, sample_processed)

__all__ = [
    "SamplingParams", "GREEDY", "request_key",
    "process_logits", "sample_processed", "accept_or_resample",
    "bonus_sample", "fold_keys",
    "CharDFA", "TokenDFA", "GrammarConstraint", "GrammarConstraintError",
    "RegexError", "byte_vocab", "compile_grammar", "json_schema_to_regex",
    "json_value_regex",
]
