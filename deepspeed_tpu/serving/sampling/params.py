"""Per-request decoding-policy parameters.

:class:`SamplingParams` is the host-side description of ONE request's
decoding policy: greedy/sampled, temperature, top-k, top-p, and the
three history penalties (repetition / presence / frequency over the
request's prompt+output token counts).  It is deliberately a plain
value object — the device never sees it.  At dispatch the scheduler
*stages* every running request's params into per-slot device arrays
(one f32/i32 lane per knob), so a mixed greedy/sampled/penalized batch
runs through ONE compiled executable per horizon/K bucket: the params
are traced inputs, never jit statics.

The staged no-op encodings are part of the contract (the pipeline's
identity guarantees key on them):

* greedy            -> ``temperature = 0.0`` (do_sample folds in)
* top-k off         -> ``top_k = 0``
* top-p off         -> ``top_p = 1.0``
* penalties off     -> ``repetition=1.0, presence=0.0, frequency=0.0``

A request whose params are all no-ops and that carries no grammar
constraint rides the legacy greedy signature untouched (token-exact,
compile-count-exact vs every release since PR 3).
"""

import numpy as np

_WIRE_KEYS = ("do_sample", "temperature", "top_k", "top_p",
              "repetition_penalty", "presence_penalty",
              "frequency_penalty")


class SamplingParams:
    """One request's decoding policy (see module docstring)."""

    __slots__ = _WIRE_KEYS

    def __init__(self, do_sample=False, temperature=1.0, top_k=0,
                 top_p=1.0, repetition_penalty=1.0, presence_penalty=0.0,
                 frequency_penalty=0.0):
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.repetition_penalty = float(repetition_penalty)
        self.presence_penalty = float(presence_penalty)
        self.frequency_penalty = float(frequency_penalty)
        self.validate()

    # ------------------------------------------------------ validation
    def validate(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if self.repetition_penalty <= 0:
            raise ValueError(f"repetition_penalty must be > 0, "
                             f"got {self.repetition_penalty}")

    # ------------------------------------------------------ properties
    @property
    def is_greedy(self):
        """THE greedy contract: ``do_sample=False`` OR ``temperature ==
        0`` is deterministic fp32 argmax, ties to the lowest id."""
        return not self.do_sample or self.temperature == 0.0

    @property
    def has_penalties(self):
        return (self.repetition_penalty != 1.0 or
                self.presence_penalty != 0.0 or
                self.frequency_penalty != 0.0)

    @property
    def needs_policy(self):
        """True when this request cannot ride the legacy greedy
        signature: it samples, or penalizes its history."""
        return not self.is_greedy or self.has_penalties

    # ----------------------------------------------------- staging
    @property
    def staged_temperature(self):
        """The per-slot temperature lane: 0.0 IS the greedy encoding
        (the device pipeline treats ``temp <= 0`` as argmax)."""
        return 0.0 if self.is_greedy else self.temperature

    # ---------------------------------------------------------- wire
    def to_dict(self):
        return {k: getattr(self, k) for k in _WIRE_KEYS}

    @classmethod
    def from_dict(cls, d, defaults=None):
        """Build from a wire dict (unknown keys rejected — a typo'd
        knob silently ignored would serve an unintended policy).
        ``defaults`` (a SamplingParams) fills the omitted keys."""
        if d is None:
            return defaults if defaults is not None else cls()
        if isinstance(d, SamplingParams):
            return d
        unknown = set(d) - set(_WIRE_KEYS)
        if unknown:
            raise ValueError(f"unknown sampling params: {sorted(unknown)}"
                             f"; valid: {list(_WIRE_KEYS)}")
        base = defaults.to_dict() if defaults is not None else {}
        base.update(d)
        return cls(**base)

    def label(self):
        if self.is_greedy and not self.has_penalties:
            return "greedy"
        parts = []
        if not self.is_greedy:
            parts.append(f"T={self.temperature:g}")
            if self.top_k:
                parts.append(f"k={self.top_k}")
            if self.top_p < 1.0:
                parts.append(f"p={self.top_p:g}")
        if self.repetition_penalty != 1.0:
            parts.append(f"rep={self.repetition_penalty:g}")
        if self.presence_penalty != 0.0:
            parts.append(f"pres={self.presence_penalty:g}")
        if self.frequency_penalty != 0.0:
            parts.append(f"freq={self.frequency_penalty:g}")
        return ",".join(parts) or "greedy"

    def __repr__(self):
        return f"SamplingParams({self.label()})"

    def __eq__(self, other):
        return isinstance(other, SamplingParams) and \
            self.to_dict() == other.to_dict()


GREEDY = SamplingParams()


def request_key(seed):
    """The per-request PRNG key as raw threefry key data (host-side,
    no device op): ``jax.random.PRNGKey(seed)`` is the uint32 pair
    ``[seed >> 32, seed & 0xffffffff]``.  Token ``n`` of the request is
    drawn from ``fold_in(key, sample_offset + n)`` — position-keyed, so
    replay after preemption or replica failover redraws NOTHING (served
    tokens are folded into the prompt) and the continuation is
    reproducible on any replica holding the same params."""
    seed = int(seed)
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    dtype=np.uint32)
