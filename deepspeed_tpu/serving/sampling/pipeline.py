"""On-device per-slot logit pipeline.

Every function here is pure JAX and traces into the fused serving
primitives (`decode_multi_policy` / `verify_multi_policy` scans and the
prefill-boundary sampler).  The design constraint that shapes all of
it: **policy parameters are per-slot device ARRAYS, never jit
statics** — a mixed greedy / sampled / penalized / grammar-constrained
batch shares one compiled executable per horizon/K bucket, and param
churn (a new temperature, a different top-p) never recompiles.

Processor chain order (documented contract, pinned by unit tests):

    fp32 cast -> grammar mask -> repetition/presence/frequency penalties
              -> temperature -> top-k -> top-p
              -> sample (argmax where temp == 0, else categorical)

The grammar mask applies FIRST so the truncation gates (top-k / top-p)
select within the ALLOWED lanes: the nucleus of the constrained
distribution.  Masking last instead would let top-p truncate away every
grammar-allowed token when none of them sits in the unconstrained
nucleus — an all--inf row whose categorical draw is garbage (a real
failure mode: one allowed continuation with low unconstrained
probability).  Since sort order puts -inf lanes past every finite lane
and the cutoff always keeps the top lane, a masked row can never lose
its last allowed token to truncation.

No-op encodings guarantee bitwise identity for untouched rows:
``temp=0`` (greedy), ``top_k=0``, ``top_p=1.0``, ``rep=1.0``,
``pres=0.0``, ``freq=0.0``, ``mask=all-True``.  Each gate is a
``jnp.where`` on the *original* lane, so a greedy row's logits pass
through the whole chain bit-exact and its argmax ties to the LOWEST
token id — the same greedy contract the legacy path pins.

Top-p uses the exact `_sample_tokens` semantics: sort descending,
softmax, cumsum, ``cutoff_idx = sum(cum < top_p)`` (smallest set whose
cumulative mass REACHES top_p; the boundary token that crosses the
threshold is kept), then drop everything strictly below the cutoff
logit — so probability ties at the cutoff are all kept.

PRNG: each slot carries a raw uint32[2] threefry key (the request's
``PRNGKey(seed)``) plus an absolute token index; token ``n`` draws from
``fold_in(key, n)``.  Position-keyed folding makes the stream
batching-independent and replayable: the same request sharded to a
different slot, chained, preempted, or failed over to another replica
draws the same randomness for the same token position.
"""

import jax
import jax.numpy as jnp


def fold_keys(keys, idx):
    """Per-slot ``fold_in``: keys [slots, 2] uint32 (raw threefry key
    data, exactly ``PRNGKey(seed)``'s buffer), idx scalar or [slots]
    int32 -> folded keys [slots, 2]."""
    idx = jnp.broadcast_to(idx, (keys.shape[0],)).astype(jnp.uint32)
    return jax.vmap(jax.random.fold_in)(keys, idx)


def process_logits(logits, counts, mask, temps, top_ks, top_ps,
                   rep_pens, pres_pens, freq_pens):
    """Apply the full per-slot processor chain; returns fp32 logits
    ready for argmax/categorical.

    logits  [slots, vocab]  any float dtype (cast fp32 here)
    counts  [slots, vocab]  int32   prompt+output token counts
    mask    [slots, vocab]  bool    grammar allowed-token mask
    temps/top_ps/rep_pens/pres_pens/freq_pens [slots] f32
    top_ks  [slots] i32
    """
    x = logits.astype(jnp.float32)
    vocab = x.shape[-1]

    # --- grammar mask FIRST (see module docstring): top-k/top-p below
    # truncate within the allowed lanes, so a constrained row always
    # keeps at least its best allowed token
    x = jnp.where(mask, x, -jnp.inf)

    seen = counts > 0

    # --- repetition penalty (CTRL rule: divide positive logits,
    # multiply negative) on tokens present in prompt+output
    rp = rep_pens[:, None]
    penalized = jnp.where(x > 0, x / rp, x * rp)
    x = jnp.where(seen & (rp != 1.0), penalized, x)

    # --- presence / frequency penalties (OpenAI semantics)
    x = x - pres_pens[:, None] * seen.astype(jnp.float32)
    x = x - freq_pens[:, None] * counts.astype(jnp.float32)

    # --- temperature (temp == 0 encodes greedy: lane untouched, the
    # sampler argmaxes it)
    t = temps[:, None]
    x = jnp.where(t > 0, x / jnp.where(t > 0, t, 1.0), x)

    # --- top-k (per-slot traced k; k <= 0 is the no-op)
    sorted_desc = jnp.sort(x, axis=-1)[:, ::-1]
    k = jnp.clip(top_ks, 0, vocab)
    kth = jnp.take_along_axis(sorted_desc,
                              jnp.maximum(k - 1, 0)[:, None], axis=-1)
    x = jnp.where((k > 0)[:, None] & (x < kth), -jnp.inf, x)

    # --- top-p over the post-top-k distribution (`cum < top_p`
    # smallest-set cutoff — identical to _sample_tokens)
    sorted_p = jnp.sort(x, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_p, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_ps[:, None], axis=-1)
    cutoff = jnp.take_along_axis(
        sorted_p, jnp.minimum(cutoff_idx, vocab - 1)[:, None], axis=-1)
    x = jnp.where((top_ps < 1.0)[:, None] & (x < cutoff), -jnp.inf, x)
    return x


def sample_processed(x, keys, tok_idx, temps):
    """Draw one token per slot from processed fp32 logits.

    Greedy rows (``temps <= 0``) take ``argmax`` (ties to lowest id);
    sampled rows draw ``categorical(fold_in(key, tok_idx))`` with a
    per-slot key — one stream per request, position-keyed.
    """
    folded = fold_keys(keys, tok_idx)
    sampled = jax.vmap(jax.random.categorical)(folded, x)
    greedy = jnp.argmax(x, axis=-1).astype(sampled.dtype)
    return jnp.where(temps <= 0.0, greedy, sampled)


def accept_or_resample(x, draft, keys, tok_idx, temps):
    """One column of lossless speculative verification.

    For a draft token ``d`` proposed by a *point-mass* drafter (our
    drafters propose tokens, not distributions: ``p_draft(d) = 1``),
    leftover-probability rejection sampling reduces to:

        accept d with prob  min(1, p_target(d) / 1) = p_target(d)
        on rejection, resample from the residual
        (p_target with d zeroed, renormalized)

    which reproduces ``p_target`` exactly for ANY proposal token — the
    distribution-exactness the frequency oracle pins.  Greedy rows keep
    the legacy token-exact rule: accept iff ``argmax == d``, resample
    is the argmax itself (which on a greedy rejection IS the residual
    argmax, since the argmax differs from d).

    Two independent draws per column come from sub-folds of the
    position key: ``fold_in(fold_in(key, n), 0)`` for the accept
    uniform, ``(..., 1)`` for the resample categorical.

    Returns ``(accept [slots] bool, fallback [slots] int32)`` where
    fallback is the resampled token to emit if this column rejects.
    """
    kcol = fold_keys(keys, tok_idx)
    ku = fold_keys(kcol, 0)
    kr = fold_keys(kcol, 1)
    probs = jax.nn.softmax(x, axis=-1)
    p_draft_tok = jnp.take_along_axis(probs, draft[:, None], axis=-1)[:, 0]
    u = jax.vmap(jax.random.uniform)(ku)
    greedy_tok = jnp.argmax(x, axis=-1)
    greedy_row = temps <= 0.0

    accept = jnp.where(greedy_row, greedy_tok == draft, u < p_draft_tok)

    # residual: zero the draft token and renormalize (categorical over
    # logits with the draft lane at -inf does both)
    x_res = jnp.where(
        jax.nn.one_hot(draft, x.shape[-1], dtype=jnp.bool_), -jnp.inf, x)
    resampled = jax.vmap(jax.random.categorical)(kr, x_res)
    fallback = jnp.where(greedy_row, greedy_tok,
                         resampled).astype(jnp.int32)
    return accept, fallback


def bonus_sample(x, keys, tok_idx, temps):
    """The bonus column: all drafts accepted — draw the next token from
    the full target distribution (argmax for greedy rows).  Uses the
    ``fold_in(key, n)`` position stream directly, matching what
    ``decode_multi_policy`` would have drawn for this position."""
    return sample_processed(x, keys, tok_idx, temps).astype(jnp.int32)
