"""Grammar-constrained decoding: host-compiled DFA -> per-step token
bitmask.

Pipeline: a JSON-schema (restricted subset) or a regex compiles ONCE on
the host to a character-level DFA (Thompson NFA -> subset
construction).  A :class:`TokenDFA` lifts the DFA to token level
against a vocabulary (token id -> string): for each DFA state it lazily
computes an allowed-token bitmask plus the state each token transitions
to, caching rows per state.  A :class:`GrammarConstraint` is the
per-request cursor the scheduler owns — it hands the dispatch its
current mask row (staged into the device grammar-mask table) and
advances on each harvested token.

Replay contract: the constraint is *derivable from the emitted tokens
alone* — on preemption-recompute or replica failover a fresh
constraint is advanced over the already-served output suffix and lands
in the identical DFA state, so constrained generation survives every
resilience path with 100% schema-valid output (the grammar oracle pins
this end-to-end).

EOS handling: the eos token is allowed iff the current state is
accepting; all other tokens follow the DFA.  A request without an eos
id finishes when the DFA is *exhausted* (accepting with no outgoing
token edges) — the scheduler checks ``done`` after each advance.

The regex dialect: literals, ``.``, classes ``[a-z0-9_]`` /
``[^...]``, escapes (``\\d \\w \\s \\n \\t`` + punctuation), grouping
``(...)``, alternation ``|``, repetition ``* + ?`` and bounded
``{m,n}`` (expanded, n <= 64).  Anchored implicitly: the whole output
must match.
"""

import json

import numpy as np

_MAX_BOUNDED_REPEAT = 64
_ALPHABET = 256  # byte-level; vocab strings index chars mod 256

_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")


# --------------------------------------------------------------- regex


class RegexError(ValueError):
    pass


class _Parser:
    """Recursive-descent regex -> AST.

    AST nodes: ("char", frozenset_of_chars) | ("cat", [nodes]) |
    ("alt", [nodes]) | ("star", node) | ("empty",)
    """

    def __init__(self, pattern):
        self.p = pattern
        self.i = 0

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise RegexError(f"unexpected {self.p[self.i]!r} at "
                             f"{self.i} in {self.p!r}")
        return node

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts = []
        while self._peek() not in (None, "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return ("empty",)
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def _repeat(self):
        node = self._atom()
        c = self._peek()
        if c == "*":
            self.i += 1
            return ("star", node)
        if c == "+":
            self.i += 1
            return ("cat", [node, ("star", node)])
        if c == "?":
            self.i += 1
            return ("alt", [node, ("empty",)])
        if c == "{":
            return self._bounded(node)
        return node

    def _bounded(self, node):
        j = self.p.index("}", self.i)
        spec = self.p[self.i + 1:j]
        self.i = j + 1
        if "," in spec:
            lo_s, hi_s = spec.split(",", 1)
            lo = int(lo_s or 0)
            hi = int(hi_s) if hi_s else None
        else:
            lo = hi = int(spec)
        if hi is not None and (hi < lo or hi > _MAX_BOUNDED_REPEAT):
            raise RegexError(f"bad bound {{{spec}}} (max "
                             f"{_MAX_BOUNDED_REPEAT})")
        parts = [node] * lo
        if hi is None:
            parts.append(("star", node))
        else:
            parts.extend([("alt", [node, ("empty",)])] * (hi - lo))
        if not parts:
            return ("empty",)
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def _atom(self):
        c = self._peek()
        if c is None:
            raise RegexError("unexpected end of pattern")
        if c == "(":
            self.i += 1
            node = self._alt()
            if self._peek() != ")":
                raise RegexError("unbalanced '('")
            self.i += 1
            return node
        if c == "[":
            return ("char", self._char_class())
        if c == ".":
            self.i += 1
            return ("char", frozenset(chr(b) for b in range(_ALPHABET)
                                      if chr(b) != "\n"))
        if c == "\\":
            self.i += 1
            return ("char", self._escape())
        if c in "*+?{":
            raise RegexError(f"dangling {c!r} at {self.i}")
        self.i += 1
        return ("char", frozenset(c))

    def _escape(self):
        c = self._peek()
        if c is None:
            raise RegexError("dangling backslash")
        self.i += 1
        table = {"d": _DIGITS, "w": _WORD, "s": _SPACE,
                 "n": frozenset("\n"), "t": frozenset("\t"),
                 "r": frozenset("\r")}
        if c in table:
            return table[c]
        if c in "DWS":
            base = {"D": _DIGITS, "W": _WORD, "S": _SPACE}[c]
            return frozenset(chr(b) for b in range(_ALPHABET)
                             if chr(b) not in base)
        return frozenset(c)  # escaped literal/punctuation

    def _char_class(self):
        assert self.p[self.i] == "["
        self.i += 1
        negate = self._peek() == "^"
        if negate:
            self.i += 1
        chars = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexError("unbalanced '['")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            if c == "\\":
                self.i += 1
                chars |= self._escape()
                continue
            self.i += 1
            if self._peek() == "-" and self.i + 1 < len(self.p) and \
                    self.p[self.i + 1] != "]":
                hi = self.p[self.i + 1]
                self.i += 2
                chars |= {chr(b) for b in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        if negate:
            chars = {chr(b) for b in range(_ALPHABET)} - chars
        return frozenset(chars)


# ------------------------------------------------------- NFA -> DFA

_MAX_DFA_STATES = 50_000


def _charmask(chars):
    """frozenset of chars -> 256-bit int bitmask."""
    m = 0
    for c in chars:
        b = ord(c)
        if b < _ALPHABET:
            m |= 1 << b
    return m


def _nfa(node, nfa, start):
    """Thompson construction; returns the accept state id.  ``nfa`` is
    (eps: list[set[int]], edges: list[list[(charmask_int, int)]])."""
    eps, edges = nfa

    def new_state():
        eps.append(set())
        edges.append([])
        return len(eps) - 1

    kind = node[0]
    if kind == "empty":
        return start
    if kind == "char":
        acc = new_state()
        edges[start].append((_charmask(node[1]), acc))
        return acc
    if kind == "cat":
        cur = start
        for part in node[1]:
            cur = _nfa(part, nfa, cur)
        return cur
    if kind == "alt":
        acc = new_state()
        for branch in node[1]:
            b_start = new_state()
            eps[start].add(b_start)
            eps[_nfa(branch, nfa, b_start)].add(acc)
        return acc
    if kind == "star":
        hub = new_state()
        eps[start].add(hub)
        body_start = new_state()
        eps[hub].add(body_start)
        eps[_nfa(node[1], nfa, body_start)].add(hub)
        return hub
    raise RegexError(f"unknown node {kind}")


def _atoms(masks):
    """Partition the 256-char alphabet into equivalence classes under
    the NFA's edge charsets — subset construction then iterates atoms
    (a handful) instead of 256 chars per state."""
    full = (1 << _ALPHABET) - 1
    parts = [full]
    for m in set(masks):
        nxt = []
        for p in parts:
            a, b = p & m, p & ~m
            if a:
                nxt.append(a)
            if b:
                nxt.append(b)
        parts = nxt
    return parts


class CharDFA:
    """Deterministic char-level automaton.

    ``trans``: list (per state) of dict char -> next state id.
    ``accepting``: set of state ids.  State 0 is the start.
    """

    def __init__(self, pattern):
        self.pattern = pattern
        ast = _Parser(pattern).parse()
        eps, edges = [set()], [[]]
        accept = _nfa(ast, (eps, edges), 0)
        n = len(eps)

        # per-NFA-state epsilon closure, computed once
        closure1 = [None] * n
        for s in range(n):
            if closure1[s] is not None:
                continue
            seen = {s}
            stack = [s]
            while stack:
                x = stack.pop()
                for t in eps[x]:
                    if t not in seen:
                        seen.add(t)
                        stack.append(t)
            closure1[s] = frozenset(seen)

        atoms = _atoms([m for es in edges for m, _ in es])
        # one representative byte per atom (lowest set bit)
        reps = [(a & -a).bit_length() - 1 for a in atoms]
        atom_chars = [[chr(b) for b in range(_ALPHABET) if (a >> b) & 1]
                      for a in atoms]

        def close(states):
            out = set()
            for s in states:
                out |= closure1[s]
            return frozenset(out)

        start = close({0})
        subsets = {start: 0}
        self.trans = [{}]
        worklist = [start]
        closure_cache = {}
        while worklist:
            subset = worklist.pop()
            sid = subsets[subset]
            out_edges = [e for s in subset for e in edges[s]]
            if not out_edges:
                continue
            for atom, rep, chars in zip(atoms, reps, atom_chars):
                tgts = frozenset(t for m, t in out_edges
                                 if (m >> rep) & 1)
                if not tgts:
                    continue
                nxt = closure_cache.get(tgts)
                if nxt is None:
                    nxt = closure_cache[tgts] = close(tgts)
                nid = subsets.get(nxt)
                if nid is None:
                    nid = subsets[nxt] = len(self.trans)
                    if nid >= _MAX_DFA_STATES:
                        raise RegexError(
                            f"grammar too large: > {_MAX_DFA_STATES} "
                            f"DFA states for {pattern[:80]!r}...")
                    self.trans.append({})
                    worklist.append(nxt)
                row = self.trans[sid]
                for c in chars:
                    row[c] = nid
        self.accepting = {sid for subset, sid in subsets.items()
                          if accept in subset}

    def step(self, state, char):
        """-> next state id, or None (dead)."""
        return self.trans[state].get(char)

    def matches(self, text):
        state = 0
        for c in text:
            state = self.step(state, c)
            if state is None:
                return False
        return state in self.accepting


# ---------------------------------------------------- token lifting


def byte_vocab(vocab_size):
    """The default token -> string map when no tokenizer text is
    available: token id i is the single char ``chr(i % 256)``.  Many
    ids alias one char — harmless for masking (all aliases get the
    same edge) and it keeps the oracle/bench decodable."""
    return [chr(i % _ALPHABET) for i in range(vocab_size)]


class TokenDFA:
    """Char DFA lifted to a token vocabulary, rows cached per state."""

    def __init__(self, pattern, vocab):
        self.dfa = CharDFA(pattern) if isinstance(pattern, str) \
            else pattern
        self.vocab = list(vocab)
        self.vocab_size = len(self.vocab)
        self._rows = {}  # state -> (mask bool[V], next int32[V])

    def row(self, state):
        cached = self._rows.get(state)
        if cached is not None:
            return cached
        mask = np.zeros(self.vocab_size, dtype=bool)
        nxt = np.full(self.vocab_size, -1, dtype=np.int32)
        for tid, text in enumerate(self.vocab):
            if not text:
                continue  # empty token would stall the DFA forever
            cur = state
            for c in text:
                cur = self.dfa.step(cur, c)
                if cur is None:
                    break
            if cur is not None:
                mask[tid] = True
                nxt[tid] = cur
        mask.setflags(write=False)
        nxt.setflags(write=False)
        self._rows[state] = (mask, nxt)
        return mask, nxt

    def is_accepting(self, state):
        return state in self.dfa.accepting


class GrammarConstraintError(ValueError):
    pass


class GrammarConstraint:
    """Per-request DFA cursor.  NOT shared between requests; the
    TokenDFA (row cache) IS shared across requests with the same spec
    via :func:`compile_grammar`'s caller-side reuse."""

    def __init__(self, token_dfa, eos_token_id=None, spec=None):
        self.tdfa = token_dfa
        self.eos_token_id = eos_token_id
        self.spec = spec  # wire dict, for journal snapshot/replay
        self.state = 0
        self.finished = False

    # ------------------------------------------------------- masking
    def token_mask(self):
        """bool[V] allowed-token mask for the CURRENT state.  The eos
        lane is overridden: allowed iff accepting (eos *ends* the
        match; its vocab text never walks the DFA)."""
        mask, _ = self.tdfa.row(self.state)
        eos = self.eos_token_id
        if eos is not None and 0 <= eos < self.tdfa.vocab_size:
            mask = mask.copy()
            mask[eos] = self.tdfa.is_accepting(self.state)
            mask.setflags(write=False)
        return mask

    @property
    def accepting(self):
        return self.tdfa.is_accepting(self.state)

    @property
    def dead(self):
        """No token (incl. eos) can be emitted from here — admission /
        harvest must fail the request rather than dispatch a row whose
        softmax would be all -inf."""
        return not self.finished and not bool(self.token_mask().any())

    @property
    def done(self):
        """Generation must stop: eos consumed, or the DFA is exhausted
        (accepting, and no token continues the match)."""
        if self.finished:
            return True
        mask, _ = self.tdfa.row(self.state)
        return self.accepting and not bool(mask.any())

    # ------------------------------------------------------ advancing
    def advance(self, token_id):
        if self.finished:
            raise GrammarConstraintError("advance past eos")
        if token_id == self.eos_token_id:
            if not self.accepting:
                raise GrammarConstraintError(
                    "eos emitted in non-accepting state")
            self.finished = True
            return
        mask, nxt = self.tdfa.row(self.state)
        if not (0 <= token_id < self.tdfa.vocab_size) or \
                not mask[token_id]:
            raise GrammarConstraintError(
                f"token {token_id} not allowed in state {self.state}")
        self.state = int(nxt[token_id])

    def replay(self, token_ids):
        """Advance over an already-served output suffix (preemption
        recompute / failover re-admission).  Raises if the suffix is
        not grammar-valid — which would mean the resilience path
        corrupted constrained output, exactly what the oracle hunts."""
        for t in token_ids:
            self.advance(int(t))
        return self

    def fresh(self):
        """A new cursor at the start state, sharing the row cache."""
        return GrammarConstraint(self.tdfa, self.eos_token_id, self.spec)

    # -------------------------------------------------------- oracle
    def accepts(self, token_ids):
        """Offline validity check: does this token sequence (optionally
        ending in eos) land in an accepting state?"""
        cur = self.fresh()
        try:
            for t in token_ids:
                cur.advance(int(t))
        except GrammarConstraintError:
            return False
        return cur.finished or cur.accepting


# ------------------------------------------------ JSON-schema subset


def _escape_literal(text):
    return "".join("\\" + c if c in r"\.[]{}()*+?|^$" else c
                   for c in text)


def json_schema_to_regex(schema, depth=0):
    """Restricted JSON-schema subset -> regex over COMPACT JSON (no
    whitespace, object keys in declaration order, all properties
    required).  Supported: string (free/bounded or enum), integer,
    number, boolean, null, enum, const, array (bounded items), object
    (fixed properties).  Free-form strings are restricted to
    ``[a-zA-Z0-9_ .-]{0,24}`` — the mask must enumerate the charset."""
    if depth > 6:
        raise GrammarConstraintError("schema nesting too deep (> 6)")
    if "const" in schema:
        return _escape_literal(json.dumps(schema["const"],
                                          separators=(",", ":")))
    if "enum" in schema:
        opts = [_escape_literal(json.dumps(v, separators=(",", ":")))
                for v in schema["enum"]]
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        max_len = min(int(schema.get("maxLength", 24)), 48)
        min_len = int(schema.get("minLength", 0))
        return ('"[a-zA-Z0-9_ .\\-]{%d,%d}"' % (min_len, max_len))
    if t == "integer":
        return "(-?(0|[1-9][0-9]{0,8}))"
    if t == "number":
        return "(-?(0|[1-9][0-9]{0,8})(\\.[0-9]{1,6})?)"
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = json_schema_to_regex(schema.get("items", {"type": "null"}),
                                    depth + 1)
        max_items = min(int(schema.get("maxItems", 4)), 8)
        min_items = int(schema.get("minItems", 0))
        if max_items == 0:
            return "\\[\\]"
        body = f"{item}(,{item}){{{max(min_items - 1, 0)},{max_items - 1}}}"
        if min_items == 0:
            return f"\\[({body})?\\]"
        return f"\\[{body}\\]"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return "\\{\\}"
        parts = []
        for key, sub in props.items():
            parts.append('"%s":%s' % (
                _escape_literal(key),
                json_schema_to_regex(sub, depth + 1)))
        return "\\{" + ",".join(parts) + "\\}"
    raise GrammarConstraintError(f"unsupported schema: {schema!r}")


def json_value_regex(depth=2):
    """Schema-free JSON value (``--response-format json_object``),
    bounded nesting.  depth 0 = scalars only."""
    scalar = ('(-?(0|[1-9][0-9]{0,6})|true|false|null|'
              '"[a-zA-Z0-9_ .\\-]{0,24}")')
    val = scalar
    for _ in range(depth):
        arr = f"\\[({val}(,{val}){{0,4}})?\\]"
        obj = f'\\{{("[a-zA-Z0-9_]{{1,12}}":{val}(,"[a-zA-Z0-9_]{{1,12}}":{val}){{0,4}})?\\}}'
        val = f"({scalar}|{arr}|{obj})"
    return val


# ----------------------------------------------------------- facade


def compile_grammar(spec, vocab, eos_token_id=None):
    """``spec`` is the wire dict a request/journal carries:

    * ``{"regex": "..."}``
    * ``{"json_schema": {...}}``
    * ``{"response_format": "json_object"}``

    ``vocab`` is token id -> string (or an int vocab size, which uses
    the byte vocab).  Returns a fresh :class:`GrammarConstraint`.
    """
    if isinstance(vocab, int):
        vocab = byte_vocab(vocab)
    if "regex" in spec:
        pattern = spec["regex"]
    elif "json_schema" in spec:
        pattern = json_schema_to_regex(spec["json_schema"])
    elif spec.get("response_format") == "json_object":
        pattern = json_value_regex()
    else:
        raise GrammarConstraintError(
            f"grammar spec needs 'regex', 'json_schema' or "
            f"'response_format': {spec!r}")
    tdfa = TokenDFA(pattern, vocab)
    return GrammarConstraint(tdfa, eos_token_id=eos_token_id, spec=dict(spec))
