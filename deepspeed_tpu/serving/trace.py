"""End-to-end request tracing for the serving tier.

The tracing core — :class:`SpanTracer`, :class:`FlightRecorder`,
:func:`merge_chrome`, :func:`prometheus_text`, the shared
:data:`EVENT_TAXONOMY` and the :data:`NULL_TRACER` singleton — lives in
:mod:`deepspeed_tpu.tracing` since the training tier adopted the same
machinery (step spans, goodput ledger, stall watchdog); this module
re-exports it unchanged for the serving tier's callers and keeps the
serving-only pieces (the device-profile integration below).

Three export surfaces over ONE span stream (Dapper-style per-request
tracing plus a flight recorder — the standard answer for "where did the
time go / what was the fleet doing" in a multi-tier serving system):

1. **Chrome-trace / Perfetto JSON** — :meth:`SpanTracer.to_chrome` /
   :meth:`SpanTracer.dump` (and ``ClusterRouter.dump_trace`` for the
   merged fleet view).  One *process* per replica, one *track* per
   slot plus scheduler/device tracks; open the file at
   https://ui.perfetto.dev or chrome://tracing.
2. **Flight recorder** — every tracer keeps its spans in a bounded
   ring, so the most recent window of activity survives the event that
   made it interesting.  :class:`FlightRecorder` dumps that window (from
   every registered tracer, correlated with the journal entry that was
   in flight) when a replica dies, a fault point fires, or an
   uncontained error escapes.
3. **Prometheus text exposition** — :func:`prometheus_text` renders the
   existing ``health()``/``summary()`` counters and gauges in the
   text-based exposition format for external scrapers (the node-exporter
   textfile-collector pattern; ``ds_serve --health-interval`` writes it
   next to the health JSONL).

Span timestamps are **host-side** ``time.monotonic()`` readings shifted
to the unix epoch at export (one offset captured per tracer, so spans
from different processes line up on the wall clock within NTP skew).
Nothing here touches the device: tracing disabled is the
:data:`NULL_TRACER` no-op (zero new jit signatures, token- and
compile-count-identical — pinned by ``tests/unit/test_trace.py``), and
tracing enabled only adds bounded host bookkeeping per dispatch.

**Trace context.**  Spans carry a request id (``rid``).  Inside one
scheduler that is the local ``Request.rid``; across the cluster the
router propagates ``trace_ctx={"trace_id": <journal rid>, "attempt":
n}`` through ``submit``/``attach_handoff`` (and the worker JSONL
protocol), so every span of one client request — prefill on the replica
that died, replay decode on the survivor, the handoff between them —
shares one id.  Failover replays additionally get an explicit
Chrome-trace flow link (``ph: s/f``) from the dead replica's last
routed span to the survivor's replay admission.
"""

import json
import os

from deepspeed_tpu.tracing import (EVENT_TAXONOMY,  # noqa: F401
                                   NULL_TRACER,
                                   FlightRecorder,
                                   SpanTracer,
                                   merge_chrome,
                                   prometheus_text,
                                   start_metrics_server)
from deepspeed_tpu.utils.logging import logger


# -------------------------------------------- device-profile integration

def profile_serving(sched, n_steps=8, trace_dir=None, depth=3):
    """Capture a JAX device profile of ``n_steps`` serving horizons and
    aggregate it per module (the dormant ``profiling/`` xplane pipeline,
    pointed at the serving loop instead of a train step).

    Returns ``{"rows": [...], "table": str}`` from
    ``profiling.module_profiler`` — measured post-fusion device time /
    flops / HBM bytes per module.  Raises RuntimeError where the
    backend records no device plane (plain CPU jax builds); callers
    (``ds_serve --profile-steps``) degrade to a warning.
    """
    from deepspeed_tpu.profiling.module_profiler import (
        aggregate_by_module, capture_trace, format_profile)

    records = capture_trace(lambda: sched.step(), n_steps=n_steps,
                            trace_dir=trace_dir)
    rows = aggregate_by_module(records, depth=depth)
    return {"rows": rows, "table": format_profile(records, depth=depth)}


def write_profile_report(report, out_dir):
    """Drop the per-module aggregation next to the trace artifacts:
    ``module_profile.json`` (rows) + ``module_profile.txt`` (table)."""
    os.makedirs(out_dir, exist_ok=True)
    jpath = os.path.join(out_dir, "module_profile.json")
    with open(jpath, "w") as f:
        json.dump(report["rows"], f, indent=2)
        f.write("\n")
    tpath = os.path.join(out_dir, "module_profile.txt")
    with open(tpath, "w") as f:
        f.write(report["table"] + "\n")
    logger.info(f"serving device profile written to {jpath}")
    return jpath, tpath
