"""Continuous-batching serving layer with a paged KV cache.

See serving/README.md for the page-table layout and the scheduler loop.
"""

from deepspeed_tpu.serving.mem_telemetry import (NULL_MEM,  # noqa: F401
                                                 PAGE_STATES,
                                                 AuditError,
                                                 MemTelemetry,
                                                 audit_pool,
                                                 classify)
from deepspeed_tpu.serving.metrics import ServingMetrics  # noqa: F401
from deepspeed_tpu.serving.page_manager import (PagedKVManager,  # noqa: F401
                                                PagePool,
                                                PagePoolExhausted)
from deepspeed_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from deepspeed_tpu.serving.sharding import (SERVING_AXIS_RULES,  # noqa: F401
                                            ServingShardingConfig,
                                            ServingShardings,
                                            pool_bytes_per_device)
from deepspeed_tpu.serving.spec_decode import (Drafter,  # noqa: F401
                                               DraftModelDrafter,
                                               NgramDrafter)
from deepspeed_tpu.serving.trace import (EVENT_TAXONOMY,  # noqa: F401
                                         NULL_TRACER,
                                         FlightRecorder,
                                         SpanTracer,
                                         merge_chrome,
                                         prometheus_text,
                                         start_metrics_server)
from deepspeed_tpu.serving.scheduler import (CANCELLED,  # noqa: F401
                                             FAILED,
                                             FINISHED,
                                             HANDOFF,
                                             SHED,
                                             QueueFull,
                                             Request,
                                             ServingScheduler)
from deepspeed_tpu.serving.cluster import (ClusterRouter,  # noqa: F401
                                           DisaggGroup,
                                           FileWalSink,
                                           Lease,
                                           LocalReplica,
                                           MemoryWalSink,
                                           ProcessReplica,
                                           ReplicaKilled,
                                           RequestJournal,
                                           RouterSupervisor,
                                           StaleEpoch,
                                           make_disaggregated_group,
                                           make_process_disaggregated_group,
                                           make_local_fleet)
from deepspeed_tpu.serving.metrics import (ClusterMetrics,  # noqa: F401
                                           HaMetrics)
