"""Disaggregated cluster serving tier: router + replica fleet.

One engine process serves one chip's worth of traffic and loses every
in-flight request when it dies.  This package is the horizontal tier
above ``ServingScheduler``:

* :class:`~deepspeed_tpu.serving.cluster.router.ClusterRouter` — the
  front end: journals every accepted request (at-most-once admission
  keyed by a client idempotency rid), routes prefix-aware across the
  fleet, detects replica death through missed health heartbeats, and
  replays a dead replica's unfinished requests token-exact onto
  survivors (at-least-once replay; the journal's emitted-token record
  makes client-visible output exactly-once).
* :class:`~deepspeed_tpu.serving.cluster.replica.LocalReplica` /
  :class:`~deepspeed_tpu.serving.cluster.replica.ProcessReplica` — an
  engine replica in this process (crash-simulated through the
  ``cluster.replica_kill`` fault point) or in a child process (killed
  for real with SIGKILL, restarted under the elastic agent's
  SIGTERM-then-SIGKILL ``term_grace_s`` contract).
* Role separation — prefill workers hand finished-prompt KV page
  chains to decode workers (``take_slot_pages`` ->
  ``attach_handoff``), degrading gracefully to unified serving when no
  prefill worker is healthy.
* Router HA (``ha.py`` + ``wal.py``) — the router itself is
  replaceable: every journal mutation is write-ahead logged through a
  pluggable sink, a :class:`~deepspeed_tpu.serving.cluster.ha.Lease`
  with monotonic epochs fences dispatch, and a
  :class:`~deepspeed_tpu.serving.cluster.ha.RouterSupervisor` promotes
  a standby on router death or lease expiry by replaying the WAL tail
  — exactly-once client output held across the takeover.

See ``docs/resilience.md`` ("Cluster failure model" and "Router HA")
for the exact at-most-once/at-least-once split and the fencing
guarantees.
"""

from deepspeed_tpu.serving.cluster.ha import (Lease,  # noqa: F401
                                              RouterSupervisor)
from deepspeed_tpu.serving.cluster.journal import (JournalEntry,  # noqa: F401
                                                   RequestJournal)
from deepspeed_tpu.serving.cluster.replica import (LocalReplica,  # noqa: F401
                                                   ProcessReplica,
                                                   ReplicaKilled,
                                                   StaleEpoch)
from deepspeed_tpu.serving.cluster.router import (ClusterRouter,  # noqa: F401
                                                  DisaggGroup,
                                                  make_disaggregated_group,
                                                  make_process_disaggregated_group,
                                                  make_local_fleet)
from deepspeed_tpu.serving.cluster.wal import (FileWalSink,  # noqa: F401
                                               MemoryWalSink)
