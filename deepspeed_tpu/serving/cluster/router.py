"""Front-end router: prefix-aware load balancing with zero-lost-request
failover over a replica fleet.

The router is pure host logic pumped cooperatively (``step()`` /
``run()``), exactly like the scheduler under it.  One pump iteration:

1. **heartbeats** — poll every replica's ``heartbeat()``; a raise (or
   ``heartbeat_misses`` consecutive misses for process replicas) marks
   the replica dead and *replays* its unfinished journal entries:
   emitted tokens fold into the prompt (the preemption-recompute
   trick), so a survivor continues the stream token-exact without
   re-emitting a single token.
2. **handoff dispatch** — finished-prompt KV chains from prefill
   workers attach to decode workers in the same group; a failed or
   faulted handoff (``cluster.handoff``) frees the pages and requeues
   the request for unified serving — contained, never lost.
3. **routing** — queued entries pick a replica: prefill workers first
   when the tier is disaggregated and one is healthy (else unified,
   counted as a degraded route); among candidates the *prefix-aware*
   policy scores each replica by how many prompt tokens its radix
   cache already holds (``PrefixCache.prefix_len``) and ties break by
   load then round-robin. ``QueueFull``/backpressure costs a bounded
   retry with exponential backoff + jitter; the retry budget exhausted
   sheds the request distinctly.
4. **pump replicas** — step each live replica once; a raise is a
   replica death (see 1), never a router death.
5. **collect** — replica-side terminal states propagate to the
   journal: finished/cancelled/failed/deadline-shed finalize; a
   capacity shed requeues under the same bounded retry budget.

Admission is **at-most-once** (client idempotency rids dedupe in the
journal), replay is **at-least-once** (a request may run partially on
several replicas), and client output is **exactly-once** (the journal
is the only token path and drops post-terminal stragglers).
"""

import json
import time
from collections import deque

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.cluster import journal as jn
from deepspeed_tpu.serving.cluster import transport as tp
from deepspeed_tpu.serving.cluster.journal import RequestJournal
from deepspeed_tpu.serving.cluster.replica import (DEAD, DRAINING, UP,
                                                   LocalReplica,
                                                   ReplicaKilled,
                                                   StaleEpoch)
from deepspeed_tpu.serving.metrics import ClusterMetrics
from deepspeed_tpu.serving.page_manager import PagePool, PagePoolExhausted
from deepspeed_tpu.serving.scheduler import ServingScheduler, _PoolsRef


class DisaggGroup:
    """A prefill/decode worker group and its transport path.

    ``transport`` is the three-way dispatch rule
    (:func:`transport.choose_transport`): ``shared_pool`` groups share
    ONE physical page pool + device-pools ref (handoff = page-id
    ownership transfer, zero copies); ``device_put`` groups give every
    worker its own pool in one process (chains move chunk-wise through
    ``export_page_chain`` -> ``jax.device_put`` ->
    ``import_page_chain``); ``wire`` groups are separate processes
    (chains move as length-prefixed frames over KV sidecar fds,
    relayed by the router).  ``pool``/``pools_ref`` are None except on
    the shared path."""

    def __init__(self, name, pool, pools_ref, transport="shared_pool"):
        self.name = name
        self.pool = pool
        self.pools_ref = pools_ref
        self.transport = transport


class _Packet:
    """A finished-prompt KV chain in flight between workers.

    ``prompt`` is the EXACT token sequence whose KV the pages hold (the
    prompt the prefill worker served) — the decode-side request must be
    keyed on it, not on the journal's current folded prompt, because
    the boundary token was already journal-emitted by the time the
    packet dispatches and folding it again would double-count it.

    Cross-pool packets also carry the transfer ``manifest`` (chunk
    count / exact bytes / digest / epoch), the source replica
    (``src_rep`` — whose pool the pages still live in until the
    transfer completes), and, on the wire path, the worker-side rid
    the source's sidecar frames are keyed by (``wire_rid``; ``pages``
    is empty and ``pool`` None — the payload never exists as router-
    side pages)."""

    __slots__ = ("entry", "group", "prompt", "pages", "length",
                 "first_tok", "pool", "manifest", "src_rep", "wire_rid")

    def __init__(self, entry, group, prompt, pages, length, first_tok,
                 pool, manifest=None, src_rep=None, wire_rid=None):
        self.entry = entry
        self.group = group
        self.prompt = prompt
        self.pages = pages
        self.length = length
        self.first_tok = first_tok
        self.pool = pool
        self.manifest = manifest
        self.src_rep = src_rep
        self.wire_rid = wire_rid


class _Transfer:
    """One in-flight cross-pool chain transfer (``device_put`` path):
    destination pages are allocated up front, then the chain moves one
    chunk per router pump — export-gather from the (live, still
    serving) source pool, ``device_put`` to the destination sharding,
    scatter-import — so the transfer overlaps both sides' ongoing
    decode horizons.  The ``cluster.handoff`` fault point fires per
    chunk, and death of either side mid-transfer aborts: partial pages
    freed on BOTH pools, request requeued unified."""

    __slots__ = ("pkt", "dst_rep", "dst_pages", "dst_pool", "chunks",
                 "seq", "t0", "nbytes", "page_bytes", "flow")

    def __init__(self, pkt, dst_rep, dst_pages, t0):
        self.pkt = pkt
        self.dst_rep = dst_rep
        self.dst_pages = dst_pages
        # captured now: a replica death drops its scheduler, but the
        # pool object is stable — partial pages stay freeable
        self.dst_pool = dst_rep.sched.kv.pool
        self.chunks = list(tp.iter_chunks(pkt.pages))
        self.seq = 0
        self.t0 = t0
        self.nbytes = 0
        src_sched = pkt.src_rep.sched
        self.page_bytes = src_sched.engine.kv_page_bytes(
            src_sched.kv.page_size, src_sched.kv_dtype_name)
        self.flow = f"handoff:{pkt.entry.rid}:{id(self)}"

    def done(self):
        return self.seq >= len(self.chunks)

    def advance_chunk(self):
        """Move ONE chunk; the caller owns fault/death policy."""
        import jax
        src_sched = self.pkt.src_rep.sched
        dst_sched = self.dst_rep.sched
        chunk = self.chunks[self.seq]
        src_chunk = chunk
        payload, _ = tp.export_chunk(src_sched.engine, src_sched.pools,
                                     src_chunk)
        # same-process fast path: both pools live on one mesh, so the
        # device_put to the destination's pool NamedSharding is a
        # resharding-free placement (on separate hosts this is the DCN
        # hop)
        pool_sh = dst_sched.engine._serving_shardings().pool
        payload = jax.device_put(payload, pool_sh)
        dst_chunk = self.dst_pages[self.seq * tp.CHUNK_PAGES:
                                   self.seq * tp.CHUNK_PAGES + len(chunk)]
        tp.import_chunk(dst_sched.engine, dst_sched._pools_ref, payload,
                        dst_chunk, dst_sched.kv.pool.num_pages)
        self.nbytes += len(chunk) * self.page_bytes
        self.seq += 1


class _WireRelay:
    """One in-flight wire transfer (``wire`` path, separate processes):
    the prefill worker's exported frames, buffered host-side by the
    source ``ProcessReplica``, streaming into the decode worker's KV
    sidecar fd a few frames per router pump.  The decode worker
    scatters each chunk on arrival and only attaches the request once
    the manifest verifies (chunk count, exact bytes, running digest)."""

    __slots__ = ("pkt", "dst_rep", "handle", "frames", "seq", "t0",
                 "flow")

    def __init__(self, pkt, dst_rep, handle, frames, t0):
        self.pkt = pkt
        self.dst_rep = dst_rep
        self.handle = handle
        self.frames = frames
        self.seq = 0
        self.t0 = t0
        self.flow = f"handoff:{pkt.entry.rid}:{id(self)}"


class ClusterRouter:
    """Load-balance requests across engine replicas; lose none."""

    def __init__(self, replicas, *, routing="prefix", retry_max=3,
                 retry_backoff_s=0.02, heartbeat_misses=3, monitor=None,
                 seed=0, term_grace_s=10.0, tracer=None,
                 flight_recorder=None, journal=None, wal=None,
                 epoch=None, lease=None, transfer_chunks_per_step=2):
        if routing not in ("prefix", "round_robin"):
            raise ValueError(f"unknown routing policy {routing!r}")
        self.replicas = list(replicas)
        self.routing = routing
        self.retry_max = int(retry_max)
        self.retry_backoff_s = float(retry_backoff_s)
        self.heartbeat_misses = int(heartbeat_misses)
        self.term_grace_s = float(term_grace_s)
        # Router HA (cluster/ha.py): `epoch` tags every replica-facing
        # call and every WAL append; `lease` is the shared authority a
        # RouterSupervisor moves between primaries.  Both None = the
        # legacy single-router mode, fencing entirely off.
        self.epoch = epoch
        self.lease = lease
        self.fenced_dispatches = 0   # replica-side StaleEpoch rejections
        self.fenced_tokens = 0       # sink-side stale-epoch token drops
        self.stale_sink_tokens = 0   # ownership-fence drops (flapping)
        if journal is not None:
            self.journal = journal
        else:
            self.journal = RequestJournal(wal=wal,
                                          epoch=0 if epoch is None
                                          else int(epoch))
        self.metrics = ClusterMetrics(monitor)
        self.step_idx = 0
        self._rr = 0
        self._rng = np.random.default_rng(seed)
        self._by_handle = {}     # id(replica handle) -> journal entry
        self._packets = deque()
        # in-flight cross-pool chain transfers, advanced
        # `transfer_chunks_per_step` chunks per pump so a transfer
        # overlaps the whole fleet's serving instead of stalling it
        self._transfers = []
        self.transfer_chunks_per_step = max(1,
                                            int(transfer_chunks_per_step))
        self._has_prefill = any(r.role == "prefill" for r in self.replicas)
        # fleet tracing: the router records routing/failover/handoff
        # spans under its own process label and hands every replica a
        # tracer of its own (the replica keeps it across die/restart);
        # dump_trace() merges the lot into ONE Chrome-trace JSON — one
        # process per replica, the rid linking a request's spans across
        # them.  flight_recorder (serving/trace.FlightRecorder) dumps
        # every source's recent-span window on replica death, correlated
        # with the journal entries that were in flight.
        self.tracer = tracer
        self.flight = flight_recorder
        if tracer is not None:
            from deepspeed_tpu.serving.trace import SpanTracer
            for rep in self.replicas:
                if hasattr(rep, "enable_trace") and \
                        getattr(rep, "tracer", None) is None:
                    rep.enable_trace(SpanTracer(process=str(rep.id)))
        if self.flight is not None:
            if tracer is not None:
                self.flight.register("router", tracer)
            for rep in self.replicas:
                if getattr(rep, "tracer", None) is not None:
                    self.flight.register(str(rep.id), rep.tracer)
                elif hasattr(rep, "trace_events"):
                    self.flight.register(
                        str(rep.id),
                        (lambda r: (lambda: list(r.trace_events)))(rep))
                if hasattr(rep, "attach_mem_flight"):
                    # replicas running memory telemetry dump their
                    # sustained-pressure episodes into the FLEET
                    # recorder (journal-correlatable rids ride along)
                    rep.attach_mem_flight(self.flight)
                if hasattr(rep, "attach_comm_flight"):
                    # and the recompile watchdog's steady-state churn
                    # dumps land in the same fleet recorder
                    rep.attach_comm_flight(self.flight)
        for rep in self.replicas:
            if rep.role == "prefill" and hasattr(rep, "set_handoff_sink"):
                if getattr(rep.group, "transport",
                           "shared_pool") == "wire":
                    rep.set_handoff_sink(
                        self._make_wire_handoff_sink(rep))
                else:
                    rep.set_handoff_sink(self._make_handoff_sink(rep))

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None, deadline_s=None, rid=None, sampling=None,
               seed=None, grammar=None, tenant=None, adapter=None):
        """Journal a request (idempotent on ``rid``) for routing at the
        next pump.  Returns the journal entry — its ``state`` /
        ``emitted`` are the client-visible truth across any number of
        replica deaths.  ``sampling``/``seed``/``grammar`` are wire
        dicts journaled verbatim: a failover resubmission replays the
        identical decoding policy (position-keyed PRNG + grammar-cursor
        replay make the continuation stream-exact, not just
        distribution-exact).  ``tenant``/``adapter`` are journaled the
        same way: a failover lands on the survivor under the same
        tenant ledger/quota/namespace and adapter weights."""
        entry, created = self.journal.admit(
            prompt, max_new_tokens, eos_token_id=eos_token_id,
            on_token=on_token, deadline_s=deadline_s, rid=rid,
            sampling=sampling, seed=seed, grammar=grammar,
            tenant=tenant, adapter=adapter)
        if created:
            self.metrics.submitted += 1
        else:
            self.metrics.duplicate_rids += 1
        return entry

    def cancel(self, rid):
        """Cancel a journaled request.  Idempotent: cancelling a
        terminal (or unknown) rid is a no-op returning False."""
        entry = self.journal.entries.get(rid)
        if entry is None or entry.state in jn.TERMINAL:
            return False
        self.journal.mark_cancel(entry)
        if entry.state == jn.QUEUED:
            self._finalize(entry, jn.CANCELLED, "cancelled in queue")
        elif entry.state == jn.ROUTED and entry.handle is not None:
            entry.handle.cancel()
        # HANDOFF packets are cancelled at dispatch (pages freed there)
        return True

    # ------------------------------------------------------------- pump
    def step(self):
        """One router pump; returns True while any journaled work is
        live.  The ``cluster.router_kill`` fault point fires first — an
        armed raise here IS the router's death, propagating to the
        RouterSupervisor (or the caller) exactly as a process crash
        would: nothing after the raise runs, the WAL holds everything
        acknowledged so far."""
        self.step_idx += 1
        faults.fire("cluster.router_kill", step=self.step_idx)
        if self.lease is not None:
            # a renewal that fails (expired, or a newer epoch holds the
            # lease) means this router is deposed; keep pumping — every
            # write is fenced — but the supervisor will notice
            self.lease.renew(self.epoch)
        now = time.monotonic()
        self._check_replicas()
        self._dispatch_handoffs(now)
        self._advance_transfers(now)
        self._route(now)
        for rep in self.replicas:
            if rep.state == DEAD:
                continue
            try:
                rep.step(self.step_idx, epoch=self.epoch)
            except StaleEpoch:
                # WE are the zombie, not the replica: never a failover
                self.fenced_dispatches += 1
            except ReplicaKilled:
                self._on_death(rep)
            except Exception:   # an uncontained replica error is a death
                self._on_death(rep)
        self._collect(now)
        return self.journal.has_live() or bool(self._packets) \
            or bool(self._transfers)

    def run(self, max_steps=100000):
        """Pump until every journaled request is terminal; returns
        ``{rid: emitted tokens}`` for the FINISHED ones."""
        for _ in range(max_steps):
            if not self.step():
                break
            if not any(rep.state != DEAD and rep.has_work()
                       for rep in self.replicas) and not self._packets \
                    and not self._transfers:
                # nothing on any device: backoff gates are the only
                # clock left — don't spin the host
                time.sleep(0.002)
        return {e.rid: list(e.emitted)
                for e in self.journal.entries.values()
                if e.state == jn.FINISHED}

    # ------------------------------------------------------- heartbeats
    def _check_replicas(self):
        for rep in self.replicas:
            if rep.state == DEAD:
                if not getattr(rep, "_death_handled", False):
                    self._on_death(rep)
                continue
            try:
                rep.heartbeat(epoch=self.epoch)
                rep.missed_beats = 0
            except StaleEpoch:
                # a deposed router's heartbeat is not a replica problem:
                # counting it as a miss would let a zombie KILL a healthy
                # replica the new primary is serving through
                self.fenced_dispatches += 1
            except Exception:
                rep.missed_beats += 1
                self.metrics.heartbeat_misses += 1
                self.metrics.event(self.step_idx, "heartbeat_miss")
                if rep.state == DEAD or \
                        rep.missed_beats >= self.heartbeat_misses:
                    self._on_death(rep)

    def _on_death(self, rep):
        if getattr(rep, "_death_handled", False):
            return
        rep._death_handled = True
        rep.die(getattr(rep, "death_reason", None) or
                "missed heartbeats")
        self.metrics.failovers += 1
        self.metrics.event(self.step_idx, "failover")
        # abort in-flight chain transfers touching the dead replica
        # BEFORE replaying its stranded entries: device_put transfers
        # free partial pages on both pools and requeue unified (pool
        # objects outlive their scheduler — same contract the shared-
        # pool path relies on); wire relays into a dead decode worker
        # just stop (the entry is ROUTED there, so the stranded scan
        # below owns the token-exact requeue)
        for t in list(self._transfers):
            if isinstance(t, _WireRelay):
                # source death is harmless here — the frames are
                # already host-buffered; only the destination matters
                if t.dst_rep is rep:
                    self._transfers.remove(t)
                    self.metrics.record_handoff_abort(self.step_idx)
            elif t.pkt.src_rep is rep or t.dst_rep is rep:
                side = "source" if t.pkt.src_rep is rep \
                    else "destination"
                self._abort_transfer(
                    t, reason=f"{side} died mid-transfer")
        # incarnation-matched: entries routed to a LATER incarnation of
        # this id (revived replica, flap race) are NOT stranded — a
        # stale death signal must never re-adopt live work
        stranded = [e for e in self.journal.live()
                    if e.state == jn.ROUTED and e.replica == rep.id and
                    e.replica_inc == getattr(rep, "incarnation", 0)]
        if self.tracer is not None:
            self.tracer.instant(
                "replica_death", cat="failover", process=str(rep.id),
                args={"reason": getattr(rep, "death_reason", None),
                      "stranded": len(stranded)})
        if self.flight is not None:
            # the post-mortem bundle: the recent-span window from every
            # source, correlated with the journal entries that were in
            # flight on the dead replica (their snapshots carry the
            # replica chain the replay will extend)
            self.flight.dump(
                f"replica_death:{rep.id}",
                journal_entry=[e.snapshot() for e in stranded],
                extra={"death_reason": getattr(rep, "death_reason",
                                               None)})
        for entry in stranded:
            self._replay(entry, dead_replica=rep.id)

    def _replay(self, entry, dead_replica=None):
        """Zero-lost failover: requeue a dead replica's entry with its
        delivered tokens folded into the prompt.  If the emitted stream
        already satisfies the request, finalize instead (a death racing
        completion must not re-serve a finished stream)."""
        if entry.handle is not None:
            self._by_handle.pop(id(entry.handle), None)
            entry.handle = None
        entry.replica = None
        if entry.finished_by_emitted():
            self._finalize(entry, jn.FINISHED)
            return
        if entry.cancel_requested:
            # cancel raced the failover: the client asked out before the
            # death — resurrecting the request onto a survivor would
            # serve work nobody wants; terminal idempotently instead
            self._finalize(entry, jn.CANCELLED,
                           "cancelled during failover replay")
            return
        entry.replays += 1
        entry.next_try = 0.0
        self.journal.requeue(entry)
        self.metrics.replays += 1
        self.metrics.replayed_tokens += len(entry.emitted)
        self.metrics.event(self.step_idx, "replay")
        if self.tracer is not None:
            # open the explicit dead-replica -> survivor flow link; the
            # matching "f" event lands when _route places the replay
            entry.trace_flow = f"replay:{entry.rid}:{entry.replays}"
            self.tracer.flow(
                "s", entry.trace_flow, "failover_replay",
                rid=entry.rid,
                process=None if dead_replica is None
                else str(dead_replica),
                args={"replays": entry.replays,
                      "tokens_folded": len(entry.emitted)})

    # ---------------------------------------------------------- routing
    def _up(self, role=None):
        out = [r for r in self.replicas if r.state == UP]
        if role is not None:
            out = [r for r in out if r.role == role]
        return out

    def _candidates(self):
        """(candidate replicas, handoff?) under the degrade policy:
        prefill workers take fresh admissions only while a decode
        worker in the same group is up; otherwise everything routes
        unified (decode/unified replicas — or, last resort, a prefill
        worker serving unified)."""
        decode_up = {id(r.group) for r in self._up("decode")}
        prefill = [r for r in self._up("prefill")
                   if id(r.group) in decode_up]
        if prefill:
            return prefill, True
        unified = [r for r in self._up() if r.role != "prefill"]
        if unified:
            return unified, False
        return self._up(), False    # prefill workers serving unified

    def _pick(self, candidates, prompt):
        if self.routing == "prefix":
            scores = [r.prefix_match_len(prompt) for r in candidates]
            best = max(scores)
            pool = [r for r, s in zip(candidates, scores) if s == best]
        else:
            pool = candidates
        min_load = min(r.load() for r in pool)
        pool = [r for r in pool if r.load() == min_load]
        rep = pool[self._rr % len(pool)]
        self._rr += 1
        return rep

    def _backoff(self, entry, now, reason):
        entry.attempts += 1
        self.metrics.retries += 1
        self.metrics.event(self.step_idx, "retry")
        if entry.attempts > self.retry_max:
            self._finalize(entry, jn.SHED,
                           f"cluster capacity: {self.retry_max} "
                           f"admission retries exhausted ({reason})")
            return
        self.journal.requeue(entry)
        # exponential backoff with jitter: synchronized retry bursts
        # are how one full replica becomes every replica's problem
        delay = self.retry_backoff_s * (2 ** (entry.attempts - 1))
        entry.next_try = now + delay * (1.0 + self._rng.random())

    def _route(self, now):
        for entry in self.journal.live():
            if entry.state != jn.QUEUED or entry.next_try > now:
                continue
            if entry.cancel_requested:
                self._finalize(entry, jn.CANCELLED, "cancelled in queue")
                continue
            if entry.deadline_abs is not None and now > entry.deadline_abs:
                self._finalize(entry, jn.SHED, "deadline expired in "
                               "router queue")
                continue
            if entry.finished_by_emitted():
                self._finalize(entry, jn.FINISHED)
                continue
            candidates, handoff = self._candidates()
            if not candidates:
                continue   # whole fleet down/draining: wait for restart
            if self._has_prefill and not handoff:
                self.metrics.degraded_routes += 1
            prompt = entry.serve_prompt()
            rep = self._pick(candidates, prompt)
            deadline_s = None if entry.deadline_abs is None \
                else max(0.001, entry.deadline_abs - now)
            try:
                handle = rep.submit(
                    prompt, entry.remaining_new,
                    eos_token_id=entry.eos_token_id,
                    deadline_s=deadline_s,
                    on_token=self._make_token_sink(entry, rep),
                    handoff=handoff,
                    trace_ctx=None if self.tracer is None else
                    {"trace_id": entry.rid, "attempt": entry.replays},
                    # the folded prompt carries len(emitted) already-
                    # served positions: sample_offset re-anchors the
                    # position-keyed PRNG and tells the scheduler which
                    # prompt suffix to replay through the grammar cursor
                    sampling=entry.sampling, seed=entry.seed,
                    grammar=entry.grammar,
                    tenant=entry.tenant, adapter=entry.adapter,
                    sample_offset=len(entry.emitted), epoch=self.epoch)
            except StaleEpoch:
                # this router is deposed: the replica refused the
                # dispatch.  Leave the entry alone — the NEW primary's
                # journal owns it now; ours is a fenced shadow.
                self.fenced_dispatches += 1
                return
            except ReplicaKilled:
                continue    # heartbeat pass will handle the body
            except ValueError as e:
                # validation error (oversize prompt, config mismatch):
                # permanent — retrying elsewhere burns the backoff
                # budget to convert a client error into a misleading
                # "cluster capacity" shed. Fail it with the message.
                self._finalize(entry, jn.FAILED,
                               f"{type(e).__name__}: {e}")
                continue
            except Exception as e:   # QueueFull et al: backpressure
                self._backoff(entry, now, f"{type(e).__name__}")
                continue
            self.journal.dispatch(entry, rep.id,
                                  getattr(rep, "incarnation", 0))
            entry.handle = handle
            self._by_handle[id(handle)] = entry
            self.metrics.routed += 1
            if self.tracer is not None:
                if entry.trace_flow is not None:
                    # close the failover link on the survivor's track
                    self.tracer.flow("f", entry.trace_flow,
                                     "failover_replay", rid=entry.rid,
                                     process=str(rep.id))
                    entry.trace_flow = None
                self.tracer.instant(
                    "route", cat="routing", rid=entry.rid,
                    process=str(rep.id),
                    args={"replica": str(rep.id),
                          "attempt": entry.attempts,
                          "replays": entry.replays,
                          "handoff": handoff})

    def _make_token_sink(self, entry, rep):
        """Token path with two fences in front of the journal:

        * **ownership** — the sink is minted for (replica, incarnation)
          at dispatch time; once the entry is replayed elsewhere (or
          the replica restarts) the pair no longer matches and a late
          token from the old stream is dropped — a flapping replica
          cannot double-emit;
        * **epoch** — under HA, a sink minted by a deposed router drops
          tokens once the lease moved on (fast path; the WAL append
          inside ``journal.token`` is the authority and would fence it
          regardless).
        """
        journal, lease, epoch = self.journal, self.lease, self.epoch
        owner = (rep.id, getattr(rep, "incarnation", 0))

        def sink(_req, tok):
            if lease is not None and lease.current_epoch != epoch:
                self.fenced_tokens += 1
                return
            if (entry.replica, entry.replica_inc) != owner:
                self.stale_sink_tokens += 1
                return
            journal.token(entry, tok)
        return sink

    # ---------------------------------------------------------- handoff
    def _make_handoff_sink(self, rep):
        def sink(req, pages, length, first_tok):
            entry = self._by_handle.pop(id(req), None)
            if entry is None:   # not a routed request (defensive)
                rep.sched.kv.pool.free(pages)
                return
            entry.handle = None
            manifest = None
            if getattr(rep.group, "transport",
                       "shared_pool") == "device_put":
                # cross-pool packet: the manifest travels into the WAL
                # so a takeover knows exactly what was in flight.  The
                # digest is empty — this path never host-stages the
                # payload (only the wire path hashes bytes).
                sched = rep.sched
                manifest = tp.make_manifest(
                    len(pages),
                    len(pages) * sched.engine.kv_page_bytes(
                        sched.kv.page_size, sched.kv_dtype_name),
                    "", 0 if self.epoch is None else self.epoch)
            self.journal.handoff(entry, rep.group.name,
                                 list(req.orig_prompt), pages, length,
                                 first_tok, manifest=manifest,
                                 src=rep.id)
            self._packets.append(
                _Packet(entry, rep.group, list(req.orig_prompt), pages,
                        length, first_tok, rep.sched.kv.pool,
                        manifest=manifest, src_rep=rep))
        return sink

    def _make_wire_handoff_sink(self, rep):
        """Handoff sink for a prefill ``ProcessReplica``: the worker
        already exported the chain onto its KV sidecar fd (and freed
        its local pages) by the time the ``handoff`` event arrives —
        the router holds the frames and relays them to a decode
        worker's sidecar.  ``pages`` is empty by construction: the
        payload never exists as router-side pool pages."""
        def sink(handle, prompt, length, first_tok, manifest):
            entry = self._by_handle.pop(id(handle), None)
            if entry is None:   # not a routed request (defensive)
                rep.drop_wire_frames(handle.rid)
                return
            entry.handle = None
            self.journal.handoff(entry, rep.group.name, list(prompt),
                                 [], length, first_tok,
                                 manifest=manifest, src=rep.id)
            self._packets.append(
                _Packet(entry, rep.group, list(prompt), [], length,
                        first_tok, None, manifest=manifest, src_rep=rep,
                        wire_rid=handle.rid))
        return sink

    def _attach_packet(self, pkt, rep, now, pages):
        """Dispatch the decode-side attach for a packet whose chain
        (or chain transfer) is complete: ``pages`` are destination-pool
        page ids (the packet's own ids on the shared path, the freshly
        imported ids after a device_put transfer).  Returns the handle
        or raises (StaleEpoch propagates; the caller owns cleanup)."""
        entry = pkt.entry
        handle = rep.attach(
            pkt.prompt, pages, pkt.length,
            pkt.first_tok, max_new_tokens=entry.remaining_new + 1,
            eos_token_id=entry.eos_token_id,
            deadline_s=None if entry.deadline_abs is None
            else max(0.001, entry.deadline_abs - now),
            on_token=self._make_token_sink(entry, rep),
            trace_ctx=None if self.tracer is None else
            {"trace_id": entry.rid, "attempt": entry.replays},
            # the boundary token (already journal-emitted) rides
            # in out_tokens on the decode side, so the offset
            # excludes it: next position = offset + len(out) =
            # len(emitted) — the stream stays position-exact
            # across the handoff
            sampling=entry.sampling, seed=entry.seed,
            grammar=entry.grammar,
            tenant=entry.tenant, adapter=entry.adapter,
            sample_offset=max(0, len(entry.emitted) - 1),
            epoch=self.epoch)
        self.journal.dispatch(entry, rep.id,
                              getattr(rep, "incarnation", 0))
        entry.handle = handle
        self._by_handle[id(handle)] = entry
        self.metrics.handoffs += 1
        self.metrics.event(self.step_idx, "handoff")
        return handle

    def _dispatch_handoffs(self, now):
        """Attach pending KV packets to decode workers, per the
        group's transport path.  Every failure mode — injected
        ``cluster.handoff`` fault, no live decode worker, attach
        refusal, source death before the chain was relayable — frees
        the pages (on whichever pools hold them) and requeues the
        request for unified serving: a handoff can be retried or
        degraded, never lost."""
        if self.lease is not None and \
                self.lease.current_epoch != self.epoch:
            # deposed: the packets (and their POOL PAGES) belong to the
            # new primary's re-driven copies — freeing or attaching them
            # here would corrupt shared state the fence exists to protect
            self.fenced_dispatches += len(self._packets)
            self._packets.clear()
            self._transfers.clear()
            return
        for _ in range(len(self._packets)):
            pkt = self._packets.popleft()
            entry = pkt.entry
            transport = getattr(pkt.group, "transport", "shared_pool")
            if entry.cancel_requested:
                self._free_packet_source(pkt)
                self._finalize(entry, jn.CANCELLED,
                               "cancelled during handoff")
                continue
            if transport == "shared_pool":
                # zero-copy path: page ids change owners, the fault
                # point fires once per packet (there are no chunks)
                try:
                    faults.fire("cluster.handoff", step=self.step_idx,
                                rid=entry.rid)
                except Exception as e:
                    pkt.pool.free(pkt.pages)
                    self._requeue_unified(
                        entry, f"handoff fault: {type(e).__name__}")
                    continue
            rep = self._pick_decode_target(pkt)
            if rep is None:
                if self._up("decode"):
                    self._packets.append(pkt)   # backpressure: retry
                    continue
                self._free_packet_source(pkt)
                self._requeue_unified(entry, "no live decode worker")
                continue
            if transport == "shared_pool":
                try:
                    self._attach_packet(pkt, rep, now, pkt.pages)
                except StaleEpoch:
                    self.fenced_dispatches += 1
                    return         # deposed: pages belong to the heir
                except Exception:
                    pkt.pool.free(pkt.pages)
                    self._requeue_unified(entry, "attach failed")
                continue
            if transport == "wire":
                self._begin_wire_transfer(pkt, rep, now)
                continue
            # device_put: allocate the destination chain up front and
            # start the chunked transfer; the attach dispatches when
            # the last chunk lands (_advance_transfers)
            try:
                dst_pages = rep.sched.kv.pool.allocate(len(pkt.pages))
            except PagePoolExhausted:
                self._packets.append(pkt)       # backpressure: retry
                continue
            t = _Transfer(pkt, rep, dst_pages, now)
            self._transfers.append(t)
            if self.tracer is not None:
                # the s/f flow pair: arrow from the source process's
                # track to the destination's, one per transfer
                self.tracer.flow(
                    "s", t.flow, "handoff_transfer", rid=entry.rid,
                    process=str(pkt.src_rep.id),
                    args={"pages": len(pkt.pages),
                          "chunks": len(t.chunks),
                          "bytes": pkt.manifest["bytes"]
                          if pkt.manifest else None})

    def _pick_decode_target(self, pkt):
        """Least-loaded live decode worker in the packet's group with
        attach headroom (the soft admission gate: never park more
        chains at a worker than it has slots — parked chains hold pool
        pages)."""
        targets = [r for r in self._up("decode") if r.group is pkt.group
                   and r.attach_backlog() < r.attach_slots()]
        return min(targets, key=lambda r: r.load()) if targets else None

    def _free_packet_source(self, pkt):
        """Free whatever source-side pages a packet still holds.  Wire
        packets hold none (the worker freed its pages at export; the
        router only buffers host frames, dropped here)."""
        if pkt.pool is not None and pkt.pages:
            pkt.pool.free(pkt.pages)
        if pkt.wire_rid is not None and pkt.src_rep is not None:
            pkt.src_rep.drop_wire_frames(pkt.wire_rid)

    # -------------------------------------------------- chain transfers
    def _begin_wire_transfer(self, pkt, rep, now):
        """Start relaying a wire packet: dispatch the attach op to the
        decode worker (it allocates pages and scatters frames as they
        arrive), then stream the buffered frames over the pumps."""
        entry = pkt.entry
        if not pkt.src_rep.wire_frames_ready(pkt.wire_rid,
                                             pkt.manifest["chunks"]):
            if pkt.src_rep.state == DEAD:
                # source SIGKILLed mid-export: the chain can never
                # complete — drop the partial frames, requeue unified
                # (token-exact: emitted tokens fold into the prompt)
                pkt.src_rep.drop_wire_frames(pkt.wire_rid)
                self.metrics.record_handoff_abort(self.step_idx)
                self._requeue_unified(
                    entry, "prefill worker died mid-transfer")
                return
            self._packets.append(pkt)       # frames still arriving
            return
        frames = pkt.src_rep.take_wire_frames(pkt.wire_rid)
        try:
            handle = rep.begin_wire_attach(
                pkt.prompt, pkt.length, pkt.first_tok,
                manifest=pkt.manifest,
                max_new_tokens=entry.remaining_new + 1,
                eos_token_id=entry.eos_token_id,
                deadline_s=None if entry.deadline_abs is None
                else max(0.001, entry.deadline_abs - now),
                on_token=self._make_token_sink(entry, rep),
                trace_ctx=None if self.tracer is None else
                {"trace_id": entry.rid, "attempt": entry.replays},
                sampling=entry.sampling, seed=entry.seed,
                grammar=entry.grammar,
                tenant=entry.tenant, adapter=entry.adapter,
                sample_offset=max(0, len(entry.emitted) - 1),
                epoch=self.epoch)
        except StaleEpoch:
            self.fenced_dispatches += 1
            return
        except Exception:
            self.metrics.record_handoff_abort(self.step_idx)
            self._requeue_unified(entry, "wire attach refused")
            return
        self.journal.dispatch(entry, rep.id,
                              getattr(rep, "incarnation", 0))
        entry.handle = handle
        self._by_handle[id(handle)] = entry
        self.metrics.handoffs += 1
        self.metrics.event(self.step_idx, "handoff")
        relay = _WireRelay(pkt, rep, handle, frames, now)
        self._transfers.append(relay)
        if self.tracer is not None:
            self.tracer.flow(
                "s", relay.flow, "handoff_transfer", rid=entry.rid,
                process=str(pkt.src_rep.id),
                args={"chunks": pkt.manifest["chunks"],
                      "bytes": pkt.manifest["bytes"]})

    def _advance_transfers(self, now):
        """Move every in-flight chain transfer forward by up to
        ``transfer_chunks_per_step`` chunks.  The per-chunk
        ``cluster.handoff`` fault fires before each chunk moves;
        faults and deaths abort the transfer with partial pages freed
        on both sides and the request requeued unified."""
        for t in list(self._transfers):
            if isinstance(t, _WireRelay):
                self._advance_wire_relay(t)
                continue
            pkt = t.pkt
            entry = pkt.entry
            if entry.cancel_requested:
                self._abort_transfer(t, requeue=False)
                self._finalize(entry, jn.CANCELLED,
                               "cancelled during handoff transfer")
                continue
            if pkt.src_rep.state == DEAD or t.dst_rep.state == DEAD:
                side = "source" if pkt.src_rep.state == DEAD \
                    else "destination"
                self._abort_transfer(
                    t, reason=f"{side} died mid-transfer")
                continue
            aborted = False
            for _ in range(self.transfer_chunks_per_step):
                if t.done():
                    break
                try:
                    faults.fire("cluster.handoff", step=self.step_idx,
                                rid=entry.rid, chunk=t.seq)
                except Exception as e:
                    self._abort_transfer(
                        t, reason=f"handoff fault at chunk {t.seq}: "
                                  f"{type(e).__name__}")
                    aborted = True
                    break
                try:
                    t.advance_chunk()
                except Exception as e:
                    self._abort_transfer(
                        t, reason=f"transfer failed at chunk {t.seq}: "
                                  f"{type(e).__name__}")
                    aborted = True
                    break
            if aborted or not t.done():
                continue
            # chain complete: source pages release, destination adopts
            self._transfers.remove(t)
            if pkt.pool is not None:
                pkt.pool.free(pkt.pages)
            ms = (time.monotonic() - t.t0) * 1e3
            try:
                self._attach_packet(pkt, t.dst_rep, now, t.dst_pages)
            except StaleEpoch:
                self.fenced_dispatches += 1
                return
            except Exception:
                t.dst_pool.free(t.dst_pages)
                self.metrics.record_handoff_abort(self.step_idx)
                self._requeue_unified(entry, "attach failed after "
                                             "transfer")
                continue
            self._record_transfer(t, pkt, ms, "device_put")

    def _advance_wire_relay(self, relay):
        """Stream the next frames of a wire transfer into the decode
        worker's KV sidecar.  The worker scatters each chunk on
        arrival; its death mid-relay is a normal replica death (the
        entry is ROUTED there — the failover pass replays it unified,
        token-exact), so the relay just stops."""
        pkt = relay.pkt
        entry = pkt.entry
        if relay.dst_rep.state == DEAD or entry.handle is None:
            # destination died (failover owns the requeue) or the
            # entry moved on: stop relaying, count the abort
            self._transfers.remove(relay)
            self.metrics.record_handoff_abort(self.step_idx)
            return
        for _ in range(self.transfer_chunks_per_step):
            if relay.seq >= len(relay.frames):
                break
            try:
                faults.fire("cluster.handoff", step=self.step_idx,
                            rid=entry.rid, chunk=relay.seq)
            except Exception as e:
                # mid-relay fault: tear down the decode side (it frees
                # its partial pages) and requeue unified.  The entry is
                # ROUTED to the decode worker — pull it back first.
                self._transfers.remove(relay)
                relay.dst_rep.abort_wire_attach(relay.handle.rid)
                self._by_handle.pop(id(relay.handle), None)
                entry.handle = None
                entry.replica = None
                self.metrics.record_handoff_abort(self.step_idx)
                self._requeue_unified(
                    entry, f"handoff fault at chunk {relay.seq}: "
                           f"{type(e).__name__}")
                return
            try:
                relay.dst_rep.send_wire_chunk(relay.handle.rid,
                                              relay.frames[relay.seq])
            except Exception:
                # broken sidecar = dying worker: stop; the heartbeat
                # pass declares the death and replays the entry
                self._transfers.remove(relay)
                self.metrics.record_handoff_abort(self.step_idx)
                return
            relay.seq += 1
        if relay.seq >= len(relay.frames):
            self._transfers.remove(relay)
            ms = (time.monotonic() - relay.t0) * 1e3
            self._record_transfer(relay, pkt, ms, "wire")

    def _record_transfer(self, t, pkt, ms, path):
        nbytes = pkt.manifest["bytes"] if pkt.manifest else t.nbytes
        chunks = pkt.manifest["chunks"] if pkt.manifest \
            else len(t.chunks)
        self.metrics.record_handoff_transfer(self.step_idx, path,
                                             nbytes, chunks, ms)
        if self.tracer is not None:
            self.tracer.flow(
                "f", t.flow, "handoff_transfer", rid=pkt.entry.rid,
                process=str(t.dst_rep.id),
                args={"bytes": nbytes, "chunks": chunks,
                      "ms": round(ms, 3), "path": path})

    def _abort_transfer(self, t, reason=None, requeue=True):
        """Tear down a device_put transfer mid-chain: free the source
        pages (the source pool outlives its scheduler — same contract
        as the shared-pool path) and the destination's pre-allocated
        chain, requeue unified.  Token-exact either way: the journal
        folds emitted tokens into the replayed prompt."""
        if t in self._transfers:
            self._transfers.remove(t)
        pkt = t.pkt
        if pkt.pool is not None:
            pkt.pool.free(pkt.pages)
        t.dst_pool.free(t.dst_pages)
        self.metrics.record_handoff_abort(self.step_idx)
        if requeue:
            self._requeue_unified(pkt.entry,
                                  reason or "transfer aborted")

    def _requeue_unified(self, entry, reason):
        if entry.finished_by_emitted():
            self._finalize(entry, jn.FINISHED)
            return
        entry.next_try = 0.0
        # `reason` rides entry.error as a transient note (cleared on
        # finish) and lands in the WAL requeue record
        self.journal.requeue(entry, error=reason)
        self.metrics.event(self.step_idx, "handoff_degrade")

    # ---------------------------------------------------------- collect
    def _collect(self, now):
        for entry in list(self.journal.live()):
            if entry.state != jn.ROUTED or entry.handle is None:
                continue
            st = entry.handle.state
            if st in ("waiting", "prefill", "running"):
                continue
            if st == "handoff":
                continue   # the sink already owns this transition
            err = entry.handle.error
            self._by_handle.pop(id(entry.handle), None)
            entry.handle = None
            entry.replica = None
            if st == "finished":
                self._finalize(entry, jn.FINISHED)
            elif st == "cancelled":
                self._finalize(entry, jn.CANCELLED, err)
            elif st == "failed":
                self._finalize(entry, jn.FAILED, err)
            elif st == "shed":
                if err is not None and "deadline" in err:
                    self._finalize(entry, jn.SHED, err)
                else:
                    # capacity shed (pool dead-end, drain): another
                    # replica may well serve it — bounded retry
                    if entry.finished_by_emitted():
                        self._finalize(entry, jn.FINISHED)
                    else:
                        self._backoff(entry, now, f"replica shed: {err}")

    def _finalize(self, entry, state, error=None):
        if entry.handle is not None:
            self._by_handle.pop(id(entry.handle), None)
        if state == jn.FINISHED:
            entry.error = None   # transient retry notes don't survive
        self.journal.finalize(entry, state, error)
        self.metrics.record_terminal(self.step_idx, state)
        if self.tracer is not None:
            # the cluster-level per-request span: submit -> terminal,
            # spanning every replica that ever held the work
            self.tracer.complete(
                "cluster_request", entry.t_submit, time.monotonic(),
                cat="request", rid=entry.rid,
                args={"state": state, "replays": entry.replays,
                      "replicas": [str(r) for r in
                                   entry.replica_history],
                      "tokens": len(entry.emitted)})

    # ------------------------------------------------- drain + restart
    def drain_replica(self, rep, max_steps=100000):
        """Rolling-restart phase 1: stop routing to ``rep`` (drain
        mode), pump the whole tier until its in-flight work finishes.
        The fleet keeps serving throughout."""
        rep.begin_drain()
        for _ in range(max_steps):
            if rep.state == DEAD or rep.drained():
                break
            self.step()
        self.metrics.drains += 1
        self.metrics.event(self.step_idx, "drain")
        if self.tracer is not None:
            self.tracer.instant("drain_complete", cat="lifecycle",
                                process=str(rep.id))

    def rolling_restart(self, term_grace_s=None):
        """Restart every live replica in sequence: drain, restart
        (process replicas get SIGTERM with the grace budget, then
        SIGKILL), resume routing.  Zero requests fail by construction —
        drained replicas finish their work before going down."""
        grace = self.term_grace_s if term_grace_s is None \
            else float(term_grace_s)
        for rep in list(self.replicas):
            if rep.state == DEAD:
                continue
            self.drain_replica(rep)
            if rep.state == DEAD:
                continue   # died mid-drain: failover already replayed
            rep.restart(term_grace_s=grace)
            rep._death_handled = False
            self.metrics.restarts += 1
            self.metrics.event(self.step_idx, "restart")
            if self.tracer is not None:
                self.tracer.instant("restart", cat="lifecycle",
                                    process=str(rep.id))

    def restart_replica(self, rep, term_grace_s=None):
        """Post-death recovery: bring a dead replica back with a fresh
        scheduler/process and rejoin it to the routing pool.  Calling
        this on a replica that is NOT dead (operator restart, flap
        recovery) first replays its in-flight entries — the fresh
        scheduler won't know them, and stranding them in ROUTED would
        hang the journal forever."""
        if rep.state != DEAD:
            inc = getattr(rep, "incarnation", 0)
            for entry in [e for e in self.journal.live()
                          if e.state == jn.ROUTED and
                          e.replica == rep.id and e.replica_inc == inc]:
                self._replay(entry, dead_replica=rep.id)
        rep.restart(term_grace_s=self.term_grace_s if term_grace_s is None
                    else term_grace_s)
        rep._death_handled = False
        self.metrics.restarts += 1

    def drain_all(self, grace_s=None, shed_queued=True):
        """Shutdown drain (the ds_serve SIGTERM path, cluster flavor):
        shed what is still queued at the router, drain every replica
        within the grace budget, shed the remainder distinctly."""
        deadline = None if grace_s is None \
            else time.monotonic() + float(grace_s)
        if shed_queued:
            for entry in self.journal.live():
                if entry.state == jn.QUEUED:
                    self._finalize(entry, jn.SHED,
                                   "shutdown drain: still queued")
        for rep in self.replicas:
            if rep.state != DEAD:
                rep.begin_drain()
        while self.journal.has_live() or self._packets or self._transfers:
            if deadline is not None and time.monotonic() > deadline:
                break
            if not self.step():
                break
        for pkt in list(self._packets):
            self._free_packet_source(pkt)
            self._finalize(pkt.entry, jn.SHED,
                           "shutdown drain: grace budget exhausted")
        self._packets.clear()
        for t in list(self._transfers):
            if isinstance(t, _WireRelay):
                self._transfers.remove(t)
                self.metrics.record_handoff_abort(self.step_idx)
                # entry is ROUTED at the decode worker — the live-entry
                # sweep below sheds it
            else:
                self._abort_transfer(t, requeue=False)
                self._finalize(t.pkt.entry, jn.SHED,
                               "shutdown drain: grace budget exhausted")
        for entry in list(self.journal.live()):
            self._finalize(entry, jn.SHED,
                           "shutdown drain: grace budget exhausted")

    # ------------------------------------------------------------ trace
    def fleet_trace(self):
        """The merged fleet Chrome-trace JSON object: the router's own
        routing/failover spans plus every replica's — live schedulers,
        DEAD replicas (their tracer outlives the dropped scheduler), and
        worker processes (spans flushed over the JSONL protocol; what a
        SIGKILLed worker flushed before dying survives here)."""
        from deepspeed_tpu.serving.trace import merge_chrome
        lists = []
        if self.tracer is not None:
            lists.append(self.tracer.serialized())
        for rep in self.replicas:
            if getattr(rep, "tracer", None) is not None:
                lists.append(rep.tracer.serialized())
            if getattr(rep, "trace_events", None):
                lists.append(list(rep.trace_events))
        return merge_chrome(lists)

    def dump_trace(self, path):
        """Write :meth:`fleet_trace` as a Chrome-trace/Perfetto JSON
        file (open at https://ui.perfetto.dev).  Returns the path."""
        import os as _os
        d = _os.path.dirname(_os.path.abspath(path))
        _os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.fleet_trace(), f)
            f.write("\n")
        return path

    # ------------------------------------------------------------- audit
    def audit(self, raise_on_error=True):
        """Fleet-wide refcount invariant audit.  Unlike a scheduler's
        own ``audit()`` — which over a SHARED disaggregated pool can
        only check structure (its peers hold references it cannot
        see) — the router sees every sharer: it groups live schedulers
        by physical pool, adds its own in-flight handoff packets (the
        pages a chain holds between detach and adopt), and runs the
        EXACT census on each pool.  This is the machine check for the
        bug class PR-7's review caught by hand: a replica die/restart
        over a shared pool that leaks (or double-frees) pages."""
        from deepspeed_tpu.serving.mem_telemetry import audit_pool
        pools = {}

        def entry(pool):
            return pools.setdefault(
                id(pool), {"pool": pool, "managers": [], "caches": [],
                           "chains": []})

        for rep in self.replicas:
            sched = getattr(rep, "sched", None)
            if sched is None:
                continue          # DEAD local replica / process replica
            ent = entry(sched.kv.pool)
            ent["managers"].append(sched.kv)
            if sched.prefix_cache is not None:
                ent["caches"].append(sched.prefix_cache)
            ent["chains"].extend(r._attach[0]
                                 for r in sched._pending_attach)
            if sched._spec is not None and \
                    getattr(sched._spec, "kv", None) is not None:
                dent = entry(sched._spec.kv.pool)
                dent["managers"].append(sched._spec.kv)
        for pkt in self._packets:
            if pkt.pool is not None:     # wire packets hold no pages
                entry(pkt.pool)["chains"].append(pkt.pages)
        for t in self._transfers:
            # mid-transfer chains hold pages on BOTH pools: the source
            # chain until the last chunk lands, the pre-allocated
            # destination chain from dispatch onward
            if isinstance(t, _WireRelay):
                continue                 # both sides worker-internal
            entry(t.pkt.pool)["chains"].append(t.pkt.pages)
            entry(t.dst_pool)["chains"].append(t.dst_pages)
        reports = []
        for i, ent in enumerate(pools.values()):
            pool = ent.pop("pool")
            reports.append(audit_pool(pool, exact=True,
                                      label=f"fleet_pool{i}",
                                      raise_on_error=raise_on_error,
                                      **ent))
        return {"ok": all(r["ok"] for r in reports), "reports": reports}

    # ------------------------------------------------------- comm ledger
    def comm_ledger(self, refresh=False):
        """Fleet comm-ledger pass: run every live local replica's
        ``ServingScheduler.comm_ledger()`` (populating its ``comm_*``
        health fields and gauges) and return ``{replica_id: {label:
        ledger}}`` — the per-signature JSON artifact CI uploads.
        Process replicas contribute through their heartbeat health
        instead (their worker computes the ledger in-process)."""
        out = {}
        for rep in self.replicas:
            sched = getattr(rep, "sched", None)
            if sched is None or not getattr(sched, "comm_telemetry",
                                            False):
                continue
            out[rep.id] = sched.comm_ledger(refresh=refresh)
        return out

    # ------------------------------------------------------------ health
    def health(self):
        """Fleet snapshot: per-replica state + aggregate counters the
        CI failover job asserts on (and uploads)."""
        hits = lookups = reused = 0
        for rep in self.replicas:
            h, lo, tr = rep.prefix_stats()
            hits += h
            lookups += lo
            reused += tr
        # fleet memory aggregation.  Free pages are a POOL property, so
        # group by physical pool (a disaggregated group's sharers would
        # otherwise multiply-count the one pool they share); process
        # replicas have no local pool object and contribute their last
        # heartbeat figure (they never share a pool cross-process).
        # Pressure counters are per-scheduler detections and sum as-is.
        mem_free = mem_episodes = mem_events = 0
        comm_bytes = steady_recompiles = 0
        comm_known = False
        seen_pools = set()
        seen_watchdogs = set()
        for rep in self.replicas:
            lh = rep.last_health or {}
            mem_episodes += lh.get("mem_pressure_episodes") or 0
            mem_events += lh.get("mem_pressure_events") or 0
            # comm/compile aggregation: local replicas read live, dead/
            # process replicas contribute their last heartbeat figure
            # (the per-scheduler ledger is static analysis — it does
            # not go stale the way load figures do)
            sched_live = getattr(rep, "sched", None) \
                if rep.state != DEAD else None
            ch = sched_live.comm_health_fields() if sched_live is not None \
                and hasattr(sched_live, "comm_health_fields") else lh
            if ch.get("comm_bytes_per_step") is not None:
                comm_known = True
                comm_bytes += ch["comm_bytes_per_step"]
            # local replicas share the ENGINE-lifetime watchdog, so
            # recompile counts are deduped by watchdog identity (like
            # free pages by pool); process replicas are separate
            # processes and sum as-is
            wd = None if sched_live is None else \
                getattr(sched_live, "compile_watchdog", None)
            if wd is not None:
                if id(wd) not in seen_watchdogs:
                    seen_watchdogs.add(id(wd))
                    steady_recompiles += wd.steady_recompiles
            elif getattr(rep, "sched", None) is None:
                # true process replicas only: a DEAD local replica's
                # heartbeat snapshots the shared engine watchdog a
                # live sibling already contributed through
                steady_recompiles += ch.get("steady_recompiles") or 0
            if rep.state == DEAD:
                continue   # stale heartbeat, no live pool to report
            sched = getattr(rep, "sched", None)
            if sched is not None:
                if id(sched.kv.pool) not in seen_pools:
                    seen_pools.add(id(sched.kv.pool))
                    mem_free += sched.kv.pool.free_pages
            else:
                mem_free += lh.get("mem_free_pages") or 0
        return {
            "step": self.step_idx,
            "routing": self.routing,
            "replicas": {
                rep.id: {
                    "state": rep.state, "role": rep.role,
                    "group": None if rep.group is None else rep.group.name,
                    "restarts": rep.restarts,
                    "missed_beats": rep.missed_beats,
                    "death_reason": getattr(rep, "death_reason", None),
                    "load": rep.load() if rep.state != DEAD else None,
                } for rep in self.replicas},
            "prefill_workers_up": len(self._up("prefill")),
            "decode_workers_up": len(self._up("decode")),
            "unified_up": len([r for r in self._up()
                               if r.role == "unified"]),
            "disaggregated": self._has_prefill,
            "degraded": self._has_prefill and
            not self._candidates()[1],
            "queued": sum(1 for e in self.journal.live()
                          if e.state == jn.QUEUED),
            "live_requests": len(self.journal.live()),
            "packets_pending": len(self._packets),
            "transfers_inflight": len(self._transfers),
            "aggregate_prefix_hit_rate":
                round(hits / lookups, 4) if lookups else 0.0,
            "aggregate_tokens_reused": reused,
            "aggregate_mem_free_pages": mem_free,
            "aggregate_mem_pressure_events": mem_events,
            "aggregate_mem_pressure_episodes": mem_episodes,
            "aggregate_comm_bytes_per_step":
                comm_bytes if comm_known else None,
            "aggregate_steady_recompiles": steady_recompiles,
            "epoch": self.epoch,
            "fenced_dispatches": self.fenced_dispatches,
            "fenced_tokens": self.fenced_tokens,
            "stale_sink_tokens": self.stale_sink_tokens,
            "wal_records": self.journal.wal_records,
            "wal_position": None if self.journal.wal is None
            else self.journal.wal.position(),
            **self.metrics.summary(),
        }


# ----------------------------------------------------------- builders

def make_local_fleet(engine, n, *, id_prefix="replica", **sched_kw):
    """N unified in-process replicas over one engine (separate pools
    and schedulers, shared compiled primitives)."""
    def factory():
        return ServingScheduler(engine, **sched_kw)
    return [LocalReplica(f"{id_prefix}{i}", factory) for i in range(n)]


def make_disaggregated_group(engine, *, name="g0", num_prefill=1,
                             num_decode=1, num_pages=64, page_size=16,
                             kv_dtype=None, transport="shared_pool",
                             **sched_kw):
    """A prefill/decode worker group under the three-path transport
    dispatch rule (:func:`transport.choose_transport`):

    * ``transport="shared_pool"`` — separate schedulers (separate slot
      tables) over ONE shared page pool and ONE device-pools ref; a
      finished prompt's KV chain transfers by page id, zero copies.
      This is the fast path when prefill and decode share devices.
    * ``transport="device_put"`` — every worker gets its OWN pool and
      device-pools ref (same process, separate HBM budgets); chains
      move chunk-wise through ``engine.export_page_chain`` ->
      ``jax.device_put`` to the destination pool's NamedSharding ->
      ``engine.import_page_chain``, overlapped with both sides' decode.
    * for separate OS processes use
      :func:`make_process_disaggregated_group` (``transport="wire"``):
      chains move as length-prefixed binary frames over dedicated KV
      sidecar fds, relayed by the router — never on the JSONL control
      wire.

    ``kv_dtype`` overrides the engine's pool dtype (int8/fp8 quantized
    pages handoff like any others on every path — their scale pools
    ride the same page ids, and the chunk payloads carry the scale
    leaves so transferred pages land with their own scales)."""
    if transport not in ("shared_pool", "device_put"):
        raise ValueError(f"unknown in-process transport {transport!r}")
    reps = []
    if transport == "shared_pool":
        pool = PagePool(num_pages, page_size)
        pools_ref = _PoolsRef(engine.init_paged_cache(
            num_pages, page_size, kv_dtype=kv_dtype))
        group = DisaggGroup(name, pool, pools_ref)

        def factory():
            return ServingScheduler(engine, num_pages=num_pages,
                                    page_size=page_size,
                                    shared_pool=pool,
                                    pools_ref=pools_ref, **sched_kw)
        for i in range(num_prefill):
            reps.append(LocalReplica(f"{name}-prefill{i}", factory,
                                     role="prefill", group=group))
        for i in range(num_decode):
            reps.append(LocalReplica(f"{name}-decode{i}", factory,
                                     role="decode", group=group))
        return reps
    group = DisaggGroup(name, None, None, transport="device_put")
    roles = [("prefill", i) for i in range(num_prefill)] + \
            [("decode", i) for i in range(num_decode)]
    for role, i in roles:
        # per-replica pool + pools ref created OUTSIDE the factory
        # closure: a die/restart builds a fresh scheduler over the SAME
        # physical pool (mirroring how a real worker's HBM allocation
        # survives its serving loop), so in-flight transfer pages stay
        # freeable and the fleet audit's census holds across restarts
        pool = PagePool(num_pages, page_size)
        pools_ref = _PoolsRef(engine.init_paged_cache(
            num_pages, page_size, kv_dtype=kv_dtype))

        def factory(pool=pool, pools_ref=pools_ref):
            return ServingScheduler(engine, num_pages=num_pages,
                                    page_size=page_size,
                                    shared_pool=pool,
                                    pools_ref=pools_ref, **sched_kw)
        reps.append(LocalReplica(f"{name}-{role}{i}", factory,
                                 role=role, group=group))
    return reps


def make_process_disaggregated_group(*, name="w0", num_prefill=1,
                                     num_decode=1, model="gpt2-tiny",
                                     **proc_kw):
    """A prefill/decode worker group over SEPARATE OS processes
    (``transport="wire"``): each worker owns a private page pool in its
    own process; finished-prompt chains leave the prefill worker as
    length-prefixed binary frames on its KV sidecar fd, the router
    relays them (with the decode-side rid rewritten) into the decode
    worker's sidecar, and the decode worker scatters each chunk on
    arrival — attach happens only after the manifest verifies (chunk
    count, exact bytes, running digest).  ``proc_kw`` passes through to
    :class:`ProcessReplica` (num_pages, page_size, kv_dtype, ...)."""
    from deepspeed_tpu.serving.cluster.replica import ProcessReplica
    group = DisaggGroup(name, None, None, transport="wire")
    reps = []
    for i in range(num_prefill):
        reps.append(ProcessReplica(f"{name}-prefill{i}", model=model,
                                   role="prefill", group=group,
                                   **proc_kw))
    for i in range(num_decode):
        reps.append(ProcessReplica(f"{name}-decode{i}", model=model,
                                   role="decode", group=group,
                                   **proc_kw))
    return reps
